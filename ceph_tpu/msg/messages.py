"""The typed message set for the storage protocol.

Re-expresses the slice of the reference's 163 message types
(src/messages/) this framework's daemons speak:

client <-> OSD:   MOSDOp / MOSDOpReply (reference MOSDOp.h)
OSD <-> OSD (EC): MOSDECSubOpWrite / ...WriteReply / ...Read /
                  ...ReadReply (reference MOSDECSubOpWrite.h etc.,
                  carrying ECSubWrite/ECSubRead from ECMsgTypes.h)
OSD <-> OSD:      MOSDPing (heartbeat, reference MOSDPing.h)
daemon <-> mon:   MMonGetMap/MMonMap, MOSDBoot, MOSDFailure,
                  MMonCommand/MMonCommandAck (pool + profile admin)

Wire layout follows message.py: JSON meta for control fields, one raw
data segment for payload bytes (write data / read replies / serialized
shard transactions).
"""

from __future__ import annotations

import json

import numpy as np

from ..osd.types import eversion_t, hobject_t, pg_t, spg_t
from ..store.object_store import Transaction
from ..store import object_store as os_
from .message import Message, register_message


# -- id plumbing -------------------------------------------------------------

def hobj_to_json(o: hobject_t) -> list:
    return [o.pool, o.name, o.key, o.snap, o.hash]


def hobj_from_json(j) -> hobject_t:
    return hobject_t(*j)


def spg_to_json(s: spg_t) -> list:
    return [s.pgid.pool, s.pgid.seed, s.shard]


def spg_from_json(j) -> spg_t:
    return spg_t(pg_t(j[0], j[1]), j[2])


# -- transaction wire form ---------------------------------------------------

def txn_to_wire(txn: Transaction) -> tuple[list, bytes]:
    """Serialize a store Transaction: op records in JSON + one data blob
    (write payloads, xattr/omap values) addressed by (offset, length)."""
    ops = []
    blob = bytearray()

    def put(b: bytes) -> list[int]:
        off = len(blob)
        blob.extend(b)
        return [off, len(b)]

    def g2j(g):
        return [hobj_to_json(g.hobj), g.generation, g.shard]

    for op in txn.ops:
        if isinstance(op, os_.OpTouch):
            ops.append(["touch", g2j(op.oid)])
        elif isinstance(op, os_.OpWrite):
            ops.append(["write", g2j(op.oid), op.offset,
                        put(op.data.tobytes())])
        elif isinstance(op, os_.OpZero):
            ops.append(["zero", g2j(op.oid), op.offset, op.length])
        elif isinstance(op, os_.OpTruncate):
            ops.append(["truncate", g2j(op.oid), op.size])
        elif isinstance(op, os_.OpRemove):
            ops.append(["remove", g2j(op.oid)])
        elif isinstance(op, os_.OpSetAttrs):
            ops.append(["setattrs", g2j(op.oid),
                        {k: put(v) for k, v in op.attrs.items()}])
        elif isinstance(op, os_.OpRmAttr):
            ops.append(["rmattr", g2j(op.oid), op.name])
        elif isinstance(op, os_.OpClone):
            ops.append(["clone", g2j(op.src), g2j(op.dst)])
        elif isinstance(op, os_.OpRename):
            ops.append(["rename", g2j(op.src), g2j(op.dst)])
        elif isinstance(op, os_.OpOmapSet):
            ops.append(["omapset", g2j(op.oid),
                        [[put(k), put(v)] for k, v in op.kv.items()]])
        elif isinstance(op, os_.OpOmapRmKeys):
            ops.append(["omaprm", g2j(op.oid), [put(k) for k in op.keys]])
        elif isinstance(op, os_.OpOmapClear):
            ops.append(["omapclear", g2j(op.oid)])
        elif isinstance(op, os_.OpOmapSetHeader):
            ops.append(["omaphdr", g2j(op.oid), put(op.data)])
        else:
            raise TypeError(f"cannot serialize {op!r}")
    return ops, bytes(blob)


def txn_from_wire(ops: list, blob: bytes) -> Transaction:
    from ..osd.types import ghobject_t

    def get(ref) -> bytes:
        off, ln = ref
        return blob[off:off + ln]

    def j2g(j):
        return ghobject_t(hobj_from_json(j[0]), j[1], j[2])

    t = Transaction()
    for rec in ops:
        kind = rec[0]
        if kind == "touch":
            t.touch(j2g(rec[1]))
        elif kind == "write":
            t.write(j2g(rec[1]), rec[2],
                    np.frombuffer(get(rec[3]), dtype=np.uint8))
        elif kind == "zero":
            t.zero(j2g(rec[1]), rec[2], rec[3])
        elif kind == "truncate":
            t.truncate(j2g(rec[1]), rec[2])
        elif kind == "remove":
            t.remove(j2g(rec[1]))
        elif kind == "setattrs":
            t.setattrs(j2g(rec[1]), {k: get(v) for k, v in rec[2].items()})
        elif kind == "rmattr":
            t.rmattr(j2g(rec[1]), rec[2])
        elif kind == "clone":
            t.clone(j2g(rec[1]), j2g(rec[2]))
        elif kind == "rename":
            t.rename(j2g(rec[1]), j2g(rec[2]))
        elif kind == "omapset":
            t.omap_setkeys(j2g(rec[1]),
                           {get(k): get(v) for k, v in rec[2]})
        elif kind == "omaprm":
            t.omap_rmkeys(j2g(rec[1]), [get(k) for k in rec[2]])
        elif kind == "omapclear":
            t.omap_clear(j2g(rec[1]))
        elif kind == "omaphdr":
            t.omap_setheader(j2g(rec[1]), get(rec[2]))
        else:
            raise ValueError(f"unknown wire op {kind}")
    return t


# -- client ops --------------------------------------------------------------

@register_message
class MOSDOp(Message):
    """Client -> primary OSD op (reference src/messages/MOSDOp.h).
    ops: list of [opname, offset, length] with write payloads
    concatenated in the data segment in op order."""

    type_id = 42

    def __init__(self, pgid: spg_t, oid: hobject_t, ops: list,
                 data: bytes = b"", tid: int = 0, epoch: int = 0,
                 snapc: list | None = None,
                 trace: dict | None = None,
                 qos: str | None = None):
        super().__init__()
        self.pgid, self.oid, self.ops = pgid, oid, ops
        self.data, self.tid, self.epoch = data, tid, epoch
        # SnapContext [seq, [snap ids]] for self-managed snapshots
        # (reference MOSDOp snap_seq + snaps)
        self.snapc = snapc
        # Dapper-style trace context (common/tracked_op.py
        # TraceContext.to_wire): stitches the client's objecter span
        # to the primary's op span across the wire
        self.trace = trace
        # client-declared QoS class (dmclock rides client info on the
        # op the same way): the mClock scheduler's per-tenant key;
        # None schedules as plain "client"
        self.qos = qos

    def to_meta(self):
        m = {"pgid": spg_to_json(self.pgid),
             "oid": hobj_to_json(self.oid),
             "ops": self.ops, "tid": self.tid, "epoch": self.epoch,
             "snapc": self.snapc}
        if self.trace is not None:
            m["trace"] = self.trace
        if self.qos is not None:
            m["qos"] = self.qos
        return m

    def data_segment(self):
        return self.data

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.oid = hobj_from_json(meta["oid"])
        self.ops, self.tid = meta["ops"], meta["tid"]
        self.epoch = meta["epoch"]
        self.snapc = meta.get("snapc")
        self.trace = meta.get("trace")
        self.qos = meta.get("qos")
        self.data = data


@register_message
class MOSDOpReply(Message):
    """reference MOSDOpReply.h."""

    type_id = 43

    def __init__(self, tid: int, result: int, data: bytes = b"",
                 epoch: int = 0):
        super().__init__()
        self.tid, self.result, self.data, self.epoch = \
            tid, result, data, epoch

    def to_meta(self):
        return {"tid": self.tid, "result": self.result, "epoch": self.epoch}

    def data_segment(self):
        return self.data

    def decode_wire(self, meta, data):
        self.tid, self.result = meta["tid"], meta["result"]
        self.epoch = meta["epoch"]
        self.data = data


# -- EC sub-ops --------------------------------------------------------------

@register_message
class MOSDECSubOpWrite(Message):
    """Primary -> shard write (reference MOSDECSubOpWrite.h carrying
    ECSubWrite: shard transaction + version + log entries + committed
    bound, ECMsgTypes.h:38 — log_entries ride the sub-write so the data
    and its history land in one shard transaction)."""

    type_id = 108

    def __init__(self, pgid: spg_t, tid: int, at_version: eversion_t,
                 txn: Transaction, log_entries: list | None = None,
                 rollforward_to: eversion_t | None = None,
                 trace: dict | None = None):
        super().__init__()
        self.pgid, self.tid, self.at_version, self.txn = \
            pgid, tid, at_version, txn
        self.log_entries = log_entries or []    # wire lists (entry_to_wire)
        self.rollforward_to = rollforward_to
        # child trace context of the primary's op span (the shard
        # holder registers its sub-op span under the same trace id)
        self.trace = trace

    def to_meta(self):
        ops, blob = txn_to_wire(self.txn)
        self._blob = blob
        rf = self.rollforward_to
        m = {"pgid": spg_to_json(self.pgid), "tid": self.tid,
             "v": [self.at_version.epoch, self.at_version.version],
             "ops": ops, "log": self.log_entries,
             "rf": [rf.epoch, rf.version] if rf is not None else None}
        if self.trace is not None:
            m["trace"] = self.trace
        return m

    def data_segment(self):
        return self._blob

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.at_version = eversion_t(*meta["v"])
        self.txn = txn_from_wire(meta["ops"], data)
        self.log_entries = meta.get("log", [])
        rf = meta.get("rf")
        self.rollforward_to = eversion_t(*rf) if rf else None
        self.trace = meta.get("trace")


@register_message
class MOSDECSubOpWriteReply(Message):
    type_id = 109

    def __init__(self, pgid: spg_t, tid: int, shard: int, result: int = 0):
        super().__init__()
        self.pgid, self.tid, self.shard, self.result = \
            pgid, tid, shard, result

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "shard": self.shard, "result": self.result}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid, self.shard = meta["tid"], meta["shard"]
        self.result = meta["result"]


@register_message
class MOSDECSubOpRead(Message):
    """Primary -> shard read (reference MOSDECSubOpRead.h / ECSubRead:
    per-shard extent list + attr wants)."""

    type_id = 110

    def __init__(self, pgid: spg_t, tid: int, oid: hobject_t,
                 off: int, length: int, want_attrs: bool = False,
                 want_omap: bool = False):
        super().__init__()
        self.pgid, self.tid, self.oid = pgid, tid, oid
        self.off, self.length, self.want_attrs = off, length, want_attrs
        self.want_omap = want_omap

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "oid": hobj_to_json(self.oid), "off": self.off,
                "len": self.length, "attrs": self.want_attrs,
                "omap": self.want_omap}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.oid = hobj_from_json(meta["oid"])
        self.off, self.length = meta["off"], meta["len"]
        self.want_attrs = meta["attrs"]
        self.want_omap = meta.get("omap", False)


@register_message
class MOSDECSubOpReadReply(Message):
    type_id = 111

    def __init__(self, pgid: spg_t, tid: int, shard: int, result: int,
                 data: bytes = b"", attrs: dict[str, bytes] | None = None,
                 size: int = -1,
                 omap: dict[bytes, bytes] | None = None,
                 omap_header: bytes = b""):
        super().__init__()
        self.pgid, self.tid, self.shard, self.result = \
            pgid, tid, shard, result
        self.data = data
        self.attrs = attrs or {}
        self.size = size  # shard object size; -1 = absent
        # omap rides only when the read asked want_omap (replicated
        # backfill pulls whole-object state across OSDs on PG split)
        self.omap = omap or {}
        self.omap_header = omap_header

    def to_meta(self):
        # attrs (+ optional omap) ride the data segment after the
        # read payload
        blob = {"a": {k: v.hex() for k, v in self.attrs.items()}}
        if self.omap:
            blob["o"] = {k.hex(): v.hex()
                         for k, v in self.omap.items()}
        if self.omap_header:
            blob["oh"] = self.omap_header.hex()
        self._attr_blob = json.dumps(blob).encode()
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "shard": self.shard, "result": self.result,
                "dlen": len(self.data), "size": self.size}

    def data_segment(self):
        return self.data + self._attr_blob

    def data_parts(self):
        # zero-concat wire path: the (up to 128 KiB+) shard payload is
        # never copied into a joined frame buffer
        return [p for p in (self.data, self._attr_blob) if p]

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid, self.shard = meta["tid"], meta["shard"]
        self.result = meta["result"]
        self.size = meta["size"]
        dlen = meta["dlen"]
        self.data = data[:dlen]
        blob = json.loads(data[dlen:].decode())
        if "a" not in blob:      # pre-omap layout: the blob IS attrs
            blob = {"a": blob}
        self.attrs = {k: bytes.fromhex(v)
                      for k, v in blob["a"].items()}
        self.omap = {bytes.fromhex(k): bytes.fromhex(v)
                     for k, v in blob.get("o", {}).items()}
        self.omap_header = bytes.fromhex(blob.get("oh", ""))


# -- heartbeat / mon ---------------------------------------------------------

@register_message
class MOSDPing(Message):
    """reference MOSDPing.h (PING / PING_REPLY)."""

    type_id = 70

    def __init__(self, from_osd: int, epoch: int = 0, is_reply: bool = False,
                 stamp: float = 0.0):
        super().__init__()
        self.from_osd, self.epoch, self.is_reply, self.stamp = \
            from_osd, epoch, is_reply, stamp

    def to_meta(self):
        return {"from": self.from_osd, "epoch": self.epoch,
                "reply": self.is_reply, "stamp": self.stamp}

    def decode_wire(self, meta, data):
        self.from_osd, self.epoch = meta["from"], meta["epoch"]
        self.is_reply, self.stamp = meta["reply"], meta["stamp"]


@register_message
class MMonGetMap(Message):
    """Map subscription / refresh request.  `have_epoch` is the
    subscriber's current osdmap epoch (reference: the `start` epoch in
    MMonSubscribe's sub_osdmap): 0 means "no map, send a full"; a
    current epoch turns the request into a ~free keepalive ack, and
    anything in the mon's incremental ring gets a delta chain instead
    of the full payload (docs/ARCHITECTURE.md "Map distribution")."""

    type_id = 4

    def __init__(self, what: str = "osdmap", have_epoch: int = 0):
        super().__init__()
        self.what = what
        self.have_epoch = have_epoch

    def to_meta(self):
        return {"what": self.what, "have": self.have_epoch}

    def decode_wire(self, meta, data):
        self.what = meta["what"]
        # absent on messages from an older sender: 0 = full map
        self.have_epoch = meta.get("have", 0)


@register_message
class MMonMap(Message):
    """OSDMap payload (reference MOSDMap.h); JSON-serialized map."""

    type_id = 5

    def __init__(self, map_json: dict | None = None):
        super().__init__()
        self.map_json = map_json or {}

    def to_meta(self):
        return {}

    def data_segment(self):
        return json.dumps(self.map_json).encode()

    def decode_wire(self, meta, data):
        self.map_json = json.loads(data.decode()) if data else {}


@register_message
class MOSDMapInc(Message):
    """Incremental osdmap range (reference MOSDMap carrying
    OSDMap::Incremental epochs): `incs` is a contiguous chain of
    committed epoch deltas (osd_map.Incremental wire JSON, oldest
    first) the subscriber applies on top of its current map; an EMPTY
    chain with `epoch` equal to the subscriber's map is the keepalive
    ack a current daemon's MMonGetMap(have_epoch=) heartbeat earns —
    bytes instead of a full-map serialization.  The mon's central
    config sections ride every send like they do on MMonMap."""

    type_id = 6

    def __init__(self, epoch: int = 0, incs: list | None = None,
                 config: dict | None = None):
        super().__init__()
        self.epoch = epoch          # the epoch the chain ends at
        self.incs = incs or []
        self.config = config or {}

    def to_meta(self):
        return {"epoch": self.epoch}

    def data_segment(self):
        return json.dumps({"incs": self.incs,
                           "config": self.config}).encode()

    def decode_wire(self, meta, data):
        self.epoch = meta["epoch"]
        body = json.loads(data.decode()) if data else {}
        self.incs = body.get("incs", [])
        self.config = body.get("config", {})


@register_message
class MOSDBoot(Message):
    """OSD announces itself up (reference MOSDBoot.h)."""

    type_id = 71

    def __init__(self, osd_id: int = -1, addr: tuple[str, int] | None = None):
        super().__init__()
        self.osd_id, self.addr = osd_id, addr

    def to_meta(self):
        return {"osd": self.osd_id, "addr": list(self.addr or ())}

    def decode_wire(self, meta, data):
        self.osd_id = meta["osd"]
        a = meta["addr"]
        self.addr = (a[0], a[1]) if a else None


@register_message
class MOSDFailure(Message):
    """Failure report to the mon (reference MOSDFailure.h)."""

    type_id = 72

    def __init__(self, reporter: int = -1, failed: int = -1,
                 epoch: int = 0):
        super().__init__()
        self.reporter, self.failed, self.epoch = reporter, failed, epoch

    def to_meta(self):
        return {"reporter": self.reporter, "failed": self.failed,
                "epoch": self.epoch}

    def decode_wire(self, meta, data):
        self.reporter, self.failed = meta["reporter"], meta["failed"]
        self.epoch = meta["epoch"]


@register_message
class MOSDSlowOpReport(Message):
    """OSD -> mon slow-op health report (the role of the reference's
    osd beacon / MMonHealthChecks feeding the SLOW_OPS warning): the
    tracker's slow_op_summary, re-sent while the condition holds and
    once more — with count 0 — to clear it."""

    type_id = 73

    def __init__(self, osd_id: int = -1, report: dict | None = None):
        super().__init__()
        self.osd_id = osd_id
        self.report = report or {}

    def to_meta(self):
        return {"osd": self.osd_id, "report": self.report}

    def decode_wire(self, meta, data):
        self.osd_id = meta["osd"]
        self.report = meta.get("report", {})


@register_message
class MPGStats(Message):
    """OSD -> mon PG-state summary (reference MPGStats via the mgr):
    per-pool degraded/misplaced/unfound object and PG counts plus the
    seeds of PGs with split/merge pushes still pending.  Feeds the
    mon's `pg stat` command, the PG_DEGRADED health check, and the
    split/merge interleave guard on pg_num decreases.  Transient
    leader-side state like slow-op reports: re-sent every stats tick,
    expired by staleness."""

    type_id = 74

    def __init__(self, osd_id: int = -1, report: dict | None = None):
        super().__init__()
        self.osd_id = osd_id
        self.report = report or {}

    def to_meta(self):
        return {"osd": self.osd_id, "report": self.report}

    def decode_wire(self, meta, data):
        self.osd_id = meta["osd"]
        self.report = meta.get("report", {})


@register_message
class MMonCommand(Message):
    """Admin command (reference MMonCommand.h; `ceph` CLI JSON dispatch)."""

    type_id = 50

    def __init__(self, cmd: dict | None = None, tid: int = 0):
        super().__init__()
        self.cmd = cmd or {}
        self.tid = tid

    def to_meta(self):
        return {"cmd": self.cmd, "tid": self.tid}

    def decode_wire(self, meta, data):
        self.cmd, self.tid = meta["cmd"], meta["tid"]


@register_message
class MMonCommandAck(Message):
    type_id = 51

    def __init__(self, tid: int = 0, result: int = 0, out: dict | None = None):
        super().__init__()
        self.tid, self.result, self.out = tid, result, out or {}

    def to_meta(self):
        return {"tid": self.tid, "result": self.result, "out": self.out}

    def decode_wire(self, meta, data):
        self.tid, self.result = meta["tid"], meta["result"]
        self.out = meta["out"]


# -- PG scan / recovery push (reference MOSDPGScan / MOSDPGPush) -------------

@register_message
class MPGList(Message):
    """List objects of a PG shard collection (reference MOSDPGScan role,
    used by backfill and scrub)."""

    type_id = 112

    def __init__(self, pgid: spg_t = None, tid: int = 0):
        super().__init__()
        self.pgid, self.tid = pgid, tid

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]


@register_message
class MPGListReply(Message):
    type_id = 113

    def __init__(self, pgid: spg_t = None, tid: int = 0,
                 oids: list | None = None):
        super().__init__()
        self.pgid, self.tid = pgid, tid
        self.oids = oids or []   # list of hobject json lists

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "oids": self.oids}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.oids = meta["oids"]


# -- cephfs (reference MClientRequest.h / MClientReply.h) --------------------

@register_message
class MClientRequest(Message):
    """FS client -> MDS metadata op (reference MClientRequest: op code
    + filepath + args; here op is a verb string and args a JSON dict)."""

    type_id = 24

    def __init__(self, op: str = "", args: dict | None = None,
                 tid: int = 0):
        super().__init__()
        self.op, self.args, self.tid = op, args or {}, tid

    def to_meta(self):
        return {"op": self.op, "args": self.args, "tid": self.tid}

    def decode_wire(self, meta, data):
        self.op, self.args, self.tid = \
            meta["op"], meta["args"], meta["tid"]


@register_message
class MClientCaps(Message):
    """MDS -> client capability message (reference MClientCaps:
    grant/revoke of file caps).  caps is a string subset of "rwc"
    (read / write / cache-and-buffer)."""

    type_id = 26

    def __init__(self, op: str = "", ino: int = 0, caps: str = "",
                 seq: int = 0):
        super().__init__()
        self.op, self.ino, self.caps, self.seq = op, ino, caps, seq

    def to_meta(self):
        return {"op": self.op, "ino": self.ino, "caps": self.caps,
                "seq": self.seq}

    def decode_wire(self, meta, data):
        self.op, self.ino, self.caps, self.seq = \
            meta["op"], meta["ino"], meta["caps"], meta["seq"]


@register_message
class MClientReply(Message):
    type_id = 25

    def __init__(self, tid: int = 0, result: int = 0,
                 out: dict | None = None):
        super().__init__()
        self.tid, self.result, self.out = tid, result, out or {}

    def to_meta(self):
        return {"tid": self.tid, "result": self.result, "out": self.out}

    def decode_wire(self, meta, data):
        self.tid, self.result, self.out = \
            meta["tid"], meta["result"], meta["out"]


# -- auth (reference MAuth.h / MAuthReply.h, cephx ticket exchange) ----------

@register_message
class MAuth(Message):
    """Client -> mon: issue me a service ticket (reference MAuth
    carrying CephXRequest; the connection itself was already
    authenticated with the client's own key)."""

    type_id = 63

    def __init__(self, entity: str = "", tid: int = 0):
        super().__init__()
        self.entity, self.tid = entity, tid

    def to_meta(self):
        return {"entity": self.entity, "tid": self.tid}

    def decode_wire(self, meta, data):
        self.entity, self.tid = meta["entity"], meta["tid"]


@register_message
class MAuthReply(Message):
    """Mon -> client: sealed ticket + session key (session key sealed
    under the CLIENT's key so only it can read it — reference
    CephXTicketBlob + encrypted session key)."""

    type_id = 64

    def __init__(self, tid: int = 0, result: int = 0,
                 ticket: str = "", sealed_key: str = ""):
        super().__init__()
        self.tid, self.result = tid, result
        self.ticket, self.sealed_key = ticket, sealed_key

    def to_meta(self):
        return {"tid": self.tid, "result": self.result,
                "ticket": self.ticket, "sealed_key": self.sealed_key}

    def decode_wire(self, meta, data):
        self.tid, self.result = meta["tid"], meta["result"]
        self.ticket, self.sealed_key = meta["ticket"], meta["sealed_key"]


# -- mon quorum (reference MMonElection.h / MMonPaxos.h) ---------------------

@register_message
class MMonPaxos(Message):
    """Mon <-> mon consensus traffic: election (propose/ack/victory)
    and paxos (collect/last/begin/accept/commit/lease) share one frame
    (the reference splits MMonElection and MMonPaxos; the field union
    is small enough to carry in one typed message here)."""

    type_id = 60

    def __init__(self, op: str = "", rank: int = -1, epoch: int = 0,
                 pn: int = 0, value: dict | None = None,
                 quorum: list | None = None,
                 committed: dict | None = None,
                 uncommitted: list | None = None):
        super().__init__()
        self.op, self.rank, self.epoch, self.pn = op, rank, epoch, pn
        self.value, self.quorum = value, quorum
        self.committed, self.uncommitted = committed, uncommitted

    def to_meta(self):
        return {"op": self.op, "rank": self.rank, "epoch": self.epoch,
                "pn": self.pn, "value": self.value,
                "quorum": self.quorum, "committed": self.committed,
                "uncommitted": self.uncommitted}

    def decode_wire(self, meta, data):
        self.op, self.rank = meta["op"], meta["rank"]
        self.epoch, self.pn = meta["epoch"], meta["pn"]
        self.value, self.quorum = meta["value"], meta["quorum"]
        self.committed = meta["committed"]
        self.uncommitted = meta["uncommitted"]


# -- peering (reference MOSDPGLog.h / MOSDPGInfo.h / PeeringState GetLog) ----

@register_message
class MPGLogQuery(Message):
    """New primary -> shard: send me your pg_info + log (reference
    PeeringState GetInfo/GetLog phases, pg_query_t)."""

    type_id = 116

    def __init__(self, pgid: spg_t = None, tid: int = 0):
        super().__init__()
        self.pgid, self.tid = pgid, tid

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]


@register_message
class MPGLogReply(Message):
    """Shard -> querying primary: pg_info + full log entries (reference
    MOSDPGLog carrying pg_log_t)."""

    type_id = 117

    def __init__(self, pgid: spg_t = None, tid: int = 0,
                 info: dict | None = None, entries: list | None = None):
        super().__init__()
        self.pgid, self.tid = pgid, tid
        self.info = info or {}          # pg_info_t.to_json()
        self.entries = entries or []    # entry_to_wire lists

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "info": self.info, "entries": self.entries}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.info, self.entries = meta["info"], meta["entries"]


@register_message
class MPGLogRollback(Message):
    """Primary -> divergent shard: roll your log back to `v` using local
    rollback state (the reference expresses this as the divergent-entry
    branch of PGLog::merge_log + ECBackend rollback transactions)."""

    type_id = 118

    def __init__(self, pgid: spg_t = None, tid: int = 0,
                 v: eversion_t = None):
        super().__init__()
        self.pgid, self.tid, self.v = pgid, tid, v

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "v": [self.v.epoch, self.v.version]}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.v = eversion_t(*meta["v"])


@register_message
class MPGLogRollbackReply(Message):
    type_id = 119

    def __init__(self, pgid: spg_t = None, tid: int = 0,
                 removed: list | None = None):
        super().__init__()
        self.pgid, self.tid = pgid, tid
        self.removed = removed or []    # hobj json lists needing recovery

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "removed": self.removed}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]
        self.removed = meta["removed"]


@register_message
class MPGActivate(Message):
    """Primary -> shard: the interval is peered; persist
    last_epoch_started (and, for a stale shard, adopt the authoritative
    log).  Reference MOSDPGLog activation + PeeringState::activate."""

    type_id = 121

    def __init__(self, pgid: spg_t = None, tid: int = 0, les: int = 0,
                 head: eversion_t = None, entries: list | None = None,
                 adopt: bool = False):
        super().__init__()
        self.pgid, self.tid, self.les = pgid, tid, les
        self.head = head or eversion_t()
        self.entries = entries or []
        self.adopt = adopt

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid,
                "les": self.les, "head": [self.head.epoch,
                                          self.head.version],
                "entries": self.entries, "adopt": self.adopt}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid, self.les = meta["tid"], meta["les"]
        self.head = eversion_t(*meta["head"])
        self.entries = meta["entries"]
        self.adopt = meta["adopt"]


@register_message
class MPGActivateReply(Message):
    type_id = 122

    def __init__(self, pgid: spg_t = None, tid: int = 0):
        super().__init__()
        self.pgid, self.tid = pgid, tid

    def to_meta(self):
        return {"pgid": spg_to_json(self.pgid), "tid": self.tid}

    def decode_wire(self, meta, data):
        self.pgid = spg_from_json(meta["pgid"])
        self.tid = meta["tid"]


# -- watch / notify (reference MWatchNotify.h, osd/Watch.h) ------------------

@register_message
class MWatchNotify(Message):
    """OSD -> watcher delivery AND watcher ack (dir field), plus the
    client->OSD watch/unwatch/notify control ops ride MOSDOp; this
    message carries the out-of-band notify fan-out."""

    type_id = 120

    def __init__(self, oid: hobject_t = None, notify_id: int = 0,
                 cookie: int = 0, payload: bytes = b"",
                 is_ack: bool = False):
        super().__init__()
        self.oid, self.notify_id, self.cookie = oid, notify_id, cookie
        self.payload, self.is_ack = payload, is_ack

    def to_meta(self):
        return {"oid": hobj_to_json(self.oid), "nid": self.notify_id,
                "cookie": self.cookie, "ack": self.is_ack}

    def data_segment(self):
        return self.payload

    def decode_wire(self, meta, data):
        self.oid = hobj_from_json(meta["oid"])
        self.notify_id, self.cookie = meta["nid"], meta["cookie"]
        self.is_ack = meta["ack"]
        self.payload = data
