"""Authentication: keyring, cephx-role tickets, connection authorizers.

Re-expresses the slice of reference src/auth/ the cluster needs:
shared-secret entities in a keyring (KeyRing.cc), mon-issued session
tickets (CephxProtocol.cc ticket blobs), per-connection authorizers
verified at accept time (AuthAuthorizeHandler), and AES-GCM secure
frame mode (msg/async/crypto_onwire.cc).
"""

from .keyring import Keyring
from .cephx import (AuthError, CephxAuth, decode_ticket, issue_ticket,
                    sign)

__all__ = ["Keyring", "CephxAuth", "AuthError", "issue_ticket",
           "decode_ticket", "sign"]
