"""cephx-role protocol: tickets, authorizers, per-connection keys.

Re-expresses reference src/auth/cephx/CephxProtocol.{h,cc} reduced to
its load-bearing shape:

- The mon issues a TICKET: {entity, caps, session_key, expiry}
  AES-GCM-sealed under the cluster SERVICE KEY.  The client cannot read
  or forge it; every daemon (which holds the service key) can.
  (reference CephXTicketBlob sealed under the service secret.)
- A connection presents an AUTHORIZER: the ticket (or a direct
  shared-key identity for daemons/mon clients) plus an HMAC proof over
  a fresh nonce+timestamp.  The acceptor verifies the proof with the
  key it can derive, and returns its own proof over the client's nonce
  (mutual authentication — reference CephXAuthorizeReply).
- Both ends derive a per-connection key = HMAC(base_key, nonce); the
  secure wire mode (crypto_onwire.cc role) AES-GCM-encrypts every
  frame under it.

Authorizer kinds and who can verify them:
  "client_key"  proof with the entity's own keyring secret — only the
                mon (keyring holder) verifies; used client->mon.
  "service"     proof with the cluster service key — any daemon
                verifies; used daemon<->daemon and daemon->mon.
  "ticket"      mon-issued ticket + proof with its session key — any
                daemon verifies; used client->osd.
"""

from __future__ import annotations

import base64
import hmac
import hashlib
import json
import os
import time

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:          # optional dep: auth protocol still
    AESGCM = None            # importable, sealing raises at use


class AuthError(Exception):
    pass


def _require_aead():
    if AESGCM is None:
        raise AuthError(
            "cephx sealed payloads need the 'cryptography' package")


FRESHNESS_WINDOW = 120.0   # seconds of clock skew tolerated


def sign(key: bytes, *parts) -> str:
    h = hmac.new(key, digestmod=hashlib.sha256)
    for p in parts:
        h.update(str(p).encode() if not isinstance(p, bytes) else p)
        h.update(b"\x00")
    return h.hexdigest()[:32]


def derive_key(base: bytes, *parts) -> bytes:
    h = hmac.new(base, digestmod=hashlib.sha256)
    for p in parts:
        h.update(str(p).encode() if not isinstance(p, bytes) else p)
        h.update(b"\x00")
    return h.digest()[:16]


def _seal(key: bytes, payload: dict) -> str:
    _require_aead()
    nonce = os.urandom(12)
    ct = AESGCM(key).encrypt(nonce, json.dumps(payload).encode(), b"")
    return base64.b64encode(nonce + ct).decode()


def _unseal(key: bytes, blob: str) -> dict:
    _require_aead()
    try:
        raw = base64.b64decode(blob)
        pt = AESGCM(key).decrypt(raw[:12], raw[12:], b"")
        return json.loads(pt.decode())
    except Exception as e:  # noqa: BLE001 - tamper/garbage
        raise AuthError(f"bad ticket: {e}") from e


def seal(key: bytes, payload: dict) -> str:
    """Public sealing helper (mon seals the session key to the client)."""
    return _seal(key, payload)


def unseal(key: bytes, blob: str) -> dict:
    return _unseal(key, blob)


def issue_ticket(service_key: bytes, entity: str, caps: str = "allow *",
                 ttl: float = 3600.0) -> tuple[str, bytes]:
    """Mon-side: returns (sealed ticket blob, session_key)."""
    session_key = os.urandom(16)
    blob = _seal(service_key, {
        "entity": entity, "caps": caps,
        "session_key": base64.b64encode(session_key).decode(),
        "expires": time.time() + ttl})
    return blob, session_key


def decode_ticket(service_key: bytes, blob: str) -> dict:
    """Daemon-side: unseal + expiry check; returns the ticket dict with
    session_key as bytes."""
    t = _unseal(service_key, blob)
    if t.get("expires", 0) < time.time():
        raise AuthError("ticket expired")
    t["session_key"] = base64.b64decode(t["session_key"])
    return t


class CephxAuth:
    """Per-process auth context plugged into the Messenger.

    Daemons get (entity, service_key [, keyring on the mon]).
    Clients get (entity, own key) and later adopt a mon-issued ticket
    via set_ticket().
    """

    def __init__(self, entity: str, key: bytes | None = None,
                 service_key: bytes | None = None,
                 keyring=None):
        self.entity = entity
        self.key = key
        self.service_key = service_key
        self.keyring = keyring
        self.ticket_blob: str | None = None
        self.ticket_session_key: bytes | None = None
        self.ticket_expires = 0.0
        # acceptor-side replay fence: an authorizer's nonce may be used
        # once within the freshness window (the challenge-response fix
        # of CVE-2018-1128, collapsed to a nonce cache so the handshake
        # stays one round trip)
        self._seen_nonces: dict[tuple[str, str], float] = {}
        import threading
        self._nonce_lock = threading.Lock()

    def set_ticket(self, blob: str, session_key: bytes,
                   expires: float = 0.0) -> None:
        self.ticket_blob = blob
        self.ticket_session_key = session_key
        self.ticket_expires = expires

    def ticket_valid(self, margin: float = 60.0) -> bool:
        return (self.ticket_blob is not None and
                (self.ticket_expires == 0.0 or
                 self.ticket_expires > time.time() + margin))

    # -- client side ---------------------------------------------------------

    def build_authorizer(self, secure: bool = False) -> dict:
        """The auth section of the HELLO frame.  `secure` (the wire
        encryption request) is covered by the hmac so a man in the
        middle cannot silently downgrade it."""
        nonce = base64.b64encode(os.urandom(12)).decode()
        ts = time.time()
        if self.service_key is not None:
            kind, key = "service", self.service_key
        elif self.ticket_valid():
            kind, key = "ticket", self.ticket_session_key
        elif self.key is not None:
            kind, key = "client_key", self.key
        else:
            raise AuthError("no credentials to build an authorizer")
        auth = {"kind": kind, "entity": self.entity, "nonce": nonce,
                "ts": ts, "secure": bool(secure),
                "hmac": sign(key, kind, self.entity, nonce, ts,
                             bool(secure))}
        if kind == "ticket":
            auth["ticket"] = self.ticket_blob
        return auth

    def check_reply(self, auth: dict, reply: dict | None) -> bytes:
        """Verify the acceptor's mutual proof, which binds the FINAL
        secure-mode decision (a man in the middle can forge neither);
        both sides must agree on secure mode or the connection fails.
        Returns the derived per-connection key."""
        key = self._base_key_for(auth["kind"])
        final = bool(reply.get("secure", False)) if reply else False
        if not reply or not hmac.compare_digest(
                str(reply.get("proof", "")),
                sign(key, "server", auth["nonce"], final)):
            raise AuthError("server failed mutual authentication")
        if final != bool(auth["secure"]):
            raise AuthError("secure-mode mismatch between endpoints")
        return derive_key(key, auth["nonce"])

    def _base_key_for(self, kind: str) -> bytes:
        if kind == "service":
            return self.service_key
        if kind == "ticket":
            return self.ticket_session_key
        return self.key

    # -- acceptor side -------------------------------------------------------

    def verify_authorizer(self, auth: dict | None,
                          server_secure: bool = False
                          ) -> tuple[dict, bytes, dict]:
        """Validate an incoming authorizer.  Returns
        (identity {entity, caps, kind, secure}, per_connection_key,
        reply dict).  `server_secure` is this acceptor's wire-crypto
        config; the final secure decision (request AND support) is
        bound into the mutual proof."""
        if not auth:
            raise AuthError("authorizer required")
        kind = auth.get("kind")
        entity = str(auth.get("entity", ""))
        nonce, ts = auth.get("nonce", ""), float(auth.get("ts", 0))
        secure = bool(auth.get("secure", False))
        now = time.time()
        if abs(now - ts) > FRESHNESS_WINDOW:
            raise AuthError("authorizer outside freshness window")
        # replay fence: each (entity, nonce) authenticates exactly once
        with self._nonce_lock:
            for k in [k for k, exp in self._seen_nonces.items()
                      if exp < now]:
                del self._seen_nonces[k]
            if (entity, nonce) in self._seen_nonces:
                raise AuthError("authorizer replayed")
        caps = "allow *"
        if kind == "service":
            if self.service_key is None:
                raise AuthError("cannot verify service authorizer")
            key = self.service_key
        elif kind == "ticket":
            if self.service_key is None:
                raise AuthError("cannot verify ticket authorizer")
            t = decode_ticket(self.service_key, auth.get("ticket", ""))
            if t["entity"] != entity:
                raise AuthError("ticket entity mismatch")
            key, caps = t["session_key"], t["caps"]
        elif kind == "client_key":
            if self.keyring is None:
                raise AuthError("cannot verify client_key authorizer")
            key = self.keyring.get(entity)
            if key is None:
                raise AuthError(f"unknown entity {entity}")
            caps = self.keyring.caps.get(entity, "")
        else:
            raise AuthError(f"unknown authorizer kind {kind!r}")
        want = sign(key, kind, entity, nonce, ts, secure)
        if not hmac.compare_digest(str(auth.get("hmac", "")), want):
            raise AuthError("bad authorizer hmac")
        # Burn the nonce only AFTER the hmac verifies: a forged
        # authorizer carrying a sniffed in-flight nonce (garbage hmac)
        # must not poison the cache and DoS the legitimate handshake.
        # Re-check under the lock: two concurrent replays of the same
        # VALID authorizer both pass the early check (TOCTOU); exactly
        # one may burn the nonce and proceed.
        with self._nonce_lock:
            if (entity, nonce) in self._seen_nonces:
                raise AuthError("authorizer replayed")
            self._seen_nonces[(entity, nonce)] = now + FRESHNESS_WINDOW
        final = bool(server_secure) and secure
        reply = {"proof": sign(key, "server", nonce, final),
                 "secure": final}
        return ({"entity": entity, "caps": caps, "kind": kind,
                 "secure": final},
                derive_key(key, nonce), reply)
