"""Keyring: entity name -> shared secret.

Re-expresses reference src/auth/KeyRing.{h,cc} at the fidelity the
cluster needs: named entities ("mon.", "osd.3", "client.admin") with
random secrets and optional caps, JSON-persisted (the reference's
INI-style keyring files carry base64 keys + caps the same way).
"""

from __future__ import annotations

import base64
import json
import os


class Keyring:
    def __init__(self) -> None:
        self._keys: dict[str, bytes] = {}
        self.caps: dict[str, str] = {}

    def gen_key(self, entity: str, caps: str = "allow *") -> bytes:
        key = os.urandom(16)
        self._keys[entity] = key
        self.caps[entity] = caps
        return key

    def add(self, entity: str, key: bytes, caps: str = "allow *") -> None:
        self._keys[entity] = bytes(key)
        self.caps[entity] = caps

    def get(self, entity: str) -> bytes | None:
        return self._keys.get(entity)

    def __contains__(self, entity: str) -> bool:
        return entity in self._keys

    # -- replication (AuthMonitor value; mon/monitor.py) ---------------------

    def to_json(self) -> dict:
        return {e: {"key": base64.b64encode(k).decode(),
                    "caps": self.caps.get(e, "")}
                for e, k in self._keys.items()}

    def replace_from_json(self, j: dict) -> None:
        """Adopt a committed auth map wholesale (the replicated value is
        the full entity set, like the committed OSDMap is the full map)."""
        self._keys = {e: base64.b64decode(rec["key"])
                      for e, rec in j.items()}
        self.caps = {e: rec.get("caps", "") for e, rec in j.items()}

    def remove(self, entity: str) -> None:
        self._keys.pop(entity, None)
        self.caps.pop(entity, None)

    def entities(self) -> list[str]:
        return sorted(self._keys)

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({e: {"key": base64.b64encode(k).decode(),
                           "caps": self.caps.get(e, "")}
                       for e, k in self._keys.items()}, f, indent=1)

    @classmethod
    def load(cls, path: str) -> "Keyring":
        kr = cls()
        with open(path) as f:
            for e, rec in json.load(f).items():
                kr.add(e, base64.b64decode(rec["key"]),
                       rec.get("caps", ""))
        return kr
