"""Compressor subsystem: pluggable codecs + registry.

Re-expresses reference src/compressor/ (Compressor.h create/registry,
plugin classes for zlib/snappy/lz4/zstd/brotli): a small uniform
compress/decompress contract behind a factory.  This image bakes in
Python's zlib/bz2/lzma, which map onto the reference's zlib/bzip2/
(zstd-role) plugins; snappy/lz4 have no local library and register as
unavailable (the registry reports what it can actually build, like the
reference's plugin load errors).

Consumers: the messenger's on-wire frame compression (reference msgr2.1
compression feature) and any host-side caller.  A TPU kernel family for
decompression is a natural future target (the byte-plane infrastructure
from the EC kernels applies); the subsystem seam is codec-shaped so a
device-backed plugin drops in.
"""

from __future__ import annotations

import bz2
import lzma
import zlib


class CompressorError(Exception):
    pass


class Compressor:
    """One codec (reference Compressor.h interface)."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes) -> bytes:
        raise NotImplementedError


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level   # wire compression favors speed

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise CompressorError(str(e)) from e


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data, 1)

    def decompress(self, data: bytes) -> bytes:
        try:
            return bz2.decompress(data)
        except (OSError, ValueError) as e:
            raise CompressorError(str(e)) from e


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=0)

    def decompress(self, data: bytes) -> bytes:
        try:
            return lzma.decompress(data)
        except lzma.LZMAError as e:
            raise CompressorError(str(e)) from e


_FACTORY = {
    "zlib": ZlibCompressor,
    "bz2": Bz2Compressor,
    "lzma": LzmaCompressor,
}
# roles the reference ships that this image cannot build (no library):
# the registry names them so callers get ENOENT-style clarity, matching
# the reference's plugin load failure surface
_UNAVAILABLE = {"snappy": "no snappy library in this image",
                "lz4": "no lz4 library in this image",
                "zstd": "no zstd library in this image"}


def create(name: str) -> Compressor:
    """Factory (reference Compressor::create)."""
    if name in _FACTORY:
        return _FACTORY[name]()
    if name in _UNAVAILABLE:
        raise CompressorError(
            f"compressor {name!r} unavailable: {_UNAVAILABLE[name]}")
    raise CompressorError(f"unknown compressor {name!r}")


def available() -> list[str]:
    return sorted(_FACTORY)
