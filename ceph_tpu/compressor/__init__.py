"""Compressor subsystem: pluggable codecs + registry.

Re-expresses reference src/compressor/ (Compressor.h create/registry,
plugin classes for zlib/snappy/lz4/zstd/brotli): a small uniform
compress/decompress contract behind a factory.  This image bakes in
Python's zlib/bz2/lzma, which map onto the reference's zlib/bzip2/
(zstd-role) plugins; snappy/lz4 have no local library and register as
unavailable (the registry reports what it can actually build, like the
reference's plugin load errors).

Consumers: the messenger's on-wire frame compression (reference msgr2.1
compression feature) and any host-side caller.  A TPU kernel family for
decompression is a natural future target (the byte-plane infrastructure
from the EC kernels applies); the subsystem seam is codec-shaped so a
device-backed plugin drops in.
"""

from __future__ import annotations

import bz2
import lzma
import zlib


class CompressorError(Exception):
    pass


# Decompression output cap: peer-supplied compressed frames must not
# amplify into unbounded allocations (a ~1MB lzma bomb expands to tens
# of GB).  Frames larger than this are a protocol violation.
MAX_DECOMPRESSED = 1 << 30


class Compressor:
    """One codec (reference Compressor.h interface)."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, data: bytes,
                   max_out: int = MAX_DECOMPRESSED) -> bytes:
        """Bounded streaming decompression shared by all codecs: ask
        the decompressor for at most max_out+1 bytes; producing more
        than max_out is rejected without materializing the bomb."""
        d = self._decompressor()
        try:
            out = d.decompress(data, max_out + 1)
        except Exception as e:  # noqa: BLE001 - codec-specific errors
            raise CompressorError(str(e)) from e
        if len(out) > max_out:
            raise CompressorError(
                f"decompressed output exceeds cap {max_out}")
        # a stream that did not finish (truncated input) or left
        # trailing bytes must fail loudly, not return partial data
        if not d.eof:
            raise CompressorError("truncated compressed stream")
        if d.unused_data:
            raise CompressorError("trailing garbage after stream")
        return out

    def _decompressor(self):
        raise NotImplementedError


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 1):
        self.level = level   # wire compression favors speed

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def _decompressor(self):
        return zlib.decompressobj()


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data, 1)

    def _decompressor(self):
        return bz2.BZ2Decompressor()


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=0)

    def _decompressor(self):
        return lzma.LZMADecompressor()


_FACTORY = {
    "zlib": ZlibCompressor,
    "bz2": Bz2Compressor,
    "lzma": LzmaCompressor,
}
# roles the reference ships that this image cannot build (no library):
# the registry names them so callers get ENOENT-style clarity, matching
# the reference's plugin load failure surface
_UNAVAILABLE = {"snappy": "no snappy library in this image",
                "lz4": "no lz4 library in this image",
                "zstd": "no zstd library in this image"}


def create(name: str) -> Compressor:
    """Factory (reference Compressor::create)."""
    if name in _FACTORY:
        return _FACTORY[name]()
    if name in _UNAVAILABLE:
        raise CompressorError(
            f"compressor {name!r} unavailable: {_UNAVAILABLE[name]}")
    raise CompressorError(f"unknown compressor {name!r}")


def available() -> list[str]:
    return sorted(_FACTORY)
