"""EC write planning: logical object ops -> per-shard store transactions.

Re-expresses reference src/osd/ECTransaction.{h,cc}:

* `PGTransaction` — the logical mutation batch PrimaryLogPG produces
  (writes/truncates/deletes/attr sets per object).
* `WritePlan` (reference ECTransaction.h:26-32) — per object: which
  stripe-aligned extents must be pre-read (RMW) and which will be
  written.
* `generate_transactions` (reference ECTransaction.cc:97) — given the
  plan and the pre-read data, produce one ObjectStore Transaction per
  shard, encoding data via ECUtil (one batched codec call per object
  extent) and folding the per-shard crc32c into HashInfo
  (encode_and_write, reference ECTransaction.cc:25-60).

TPU-first difference: planning is pure host logic, but all encodes in a
transaction batch are concatenated into a single codec launch by the
backend (see ec_backend.py) — the plan records extents, not per-stripe
work items.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..store.object_store import Transaction
from .ec_util import HINFO_KEY, HashInfo, StripeInfo
from .types import ghobject_t, hobject_t


# -- logical ops (PGTransaction) --------------------------------------------

@dataclass
class PGWrite:
    offset: int
    data: np.ndarray

    def __post_init__(self):
        self.data = np.asarray(self.data, dtype=np.uint8).ravel()

    @property
    def end(self) -> int:
        return self.offset + self.data.size


@dataclass
class PGObjectOp:
    """All mutations for one object within a PGTransaction."""
    writes: list[PGWrite] = field(default_factory=list)
    truncate_to: int | None = None
    delete: bool = False
    attrs: dict[str, bytes | None] = field(default_factory=dict)
    # omap (replicated pools only — the reference rejects omap on EC
    # pools via the SUPPORTS_OMAP pool flag, and so does the OSD op
    # switch here).  Mutations keep their op-vector order: rm-then-set
    # and set-then-clear must commit different final states.
    omap_ops: list[tuple] = field(default_factory=list)


class PGTransaction:
    def __init__(self) -> None:
        self.ops: dict[hobject_t, PGObjectOp] = {}

    def obj(self, oid: hobject_t) -> PGObjectOp:
        return self.ops.setdefault(oid, PGObjectOp())

    def write(self, oid: hobject_t, off: int, data) -> None:
        self.obj(oid).writes.append(PGWrite(off, data))

    def truncate(self, oid: hobject_t, size: int) -> None:
        self.obj(oid).truncate_to = size

    def delete(self, oid: hobject_t) -> None:
        # delete supersedes anything staged before it in this op
        # vector; mutations staged AFTER it recreate the object
        # (reference do_osd_ops applies the vector sequentially)
        op = self.obj(oid)
        op.writes.clear()
        op.attrs.clear()
        op.omap_ops.clear()
        op.truncate_to = None
        op.delete = True

    def setattr(self, oid: hobject_t, name: str, value: bytes | None) -> None:
        self.obj(oid).attrs[name] = value

    def omap_setkeys(self, oid: hobject_t, kv: dict[bytes, bytes]) -> None:
        self.obj(oid).omap_ops.append(("set", dict(kv)))

    def omap_rmkeys(self, oid: hobject_t, keys) -> None:
        self.obj(oid).omap_ops.append(("rm", list(keys)))

    def omap_clear(self, oid: hobject_t) -> None:
        self.obj(oid).omap_ops.append(("clear",))

    def omap_setheader(self, oid: hobject_t, data: bytes) -> None:
        self.obj(oid).omap_ops.append(("header", bytes(data)))


# -- plan --------------------------------------------------------------------

@dataclass
class Extent:
    off: int
    length: int

    @property
    def end(self) -> int:
        return self.off + self.length


@dataclass
class WritePlan:
    """reference ECTransaction.h:26: to_read/will_write per object."""
    to_read: dict[hobject_t, list[Extent]] = field(default_factory=dict)
    will_write: dict[hobject_t, list[Extent]] = field(default_factory=dict)
    hash_infos: dict[hobject_t, HashInfo] = field(default_factory=dict)
    sizes: dict[hobject_t, int] = field(default_factory=dict)


def _merge_extents(extents: list[Extent]) -> list[Extent]:
    out: list[Extent] = []
    for e in sorted(extents, key=lambda x: x.off):
        if out and e.off <= out[-1].end:
            out[-1] = Extent(out[-1].off,
                             max(out[-1].end, e.end) - out[-1].off)
        else:
            out.append(Extent(e.off, e.length))
    return out


def get_write_plan(sinfo: StripeInfo, txn: PGTransaction,
                   get_hinfo, get_size, reset_hinfo=None) -> WritePlan:
    """Round writes out to stripe bounds; extents not fully covered by
    the new data and inside the current object need an RMW pre-read
    (reference ECTransaction get_write_plan semantics exercised by
    src/test/osd/test_ec_transaction.cc:29-85).  `reset_hinfo(oid)`,
    when given, must swap a FRESH HashInfo into the caller's projected
    chain and return it (used for delete-then-recreate vectors)."""
    plan = WritePlan()
    for oid, op in txn.ops.items():
        size = get_size(oid)
        plan.sizes[oid] = size
        plan.hash_infos[oid] = get_hinfo(oid)
        if op.delete and not op.writes:
            continue
        if op.delete:
            # delete-then-recreate in one vector (reference do_osd_ops
            # evolves obs through the vector; the replicated backend's
            # _to_store_txn already recreates): the plan must see the
            # FRESH object — no RMW pre-reads of pre-delete bytes, size
            # 0, reset hinfo.  `reset_hinfo` swaps a NEW instance into
            # the caller's projected chain so this op and later queued
            # ops seed from the recreate, while earlier in-flight ops
            # keep folding onto the instance they already planned
            # against (mutating the shared one in place would corrupt
            # their crc chains).  Rollback still restores the old
            # object from the generation kept at commit time.
            size = 0
            plan.sizes[oid] = 0
            if reset_hinfo is not None:
                plan.hash_infos[oid] = reset_hinfo(oid)
            else:
                old = plan.hash_infos[oid]
                plan.hash_infos[oid] = HashInfo.make(
                    len(old.cumulative_shard_hashes))
        will, read = [], []
        for w in op.writes:
            start = sinfo.logical_to_prev_stripe_offset(w.offset)
            end = sinfo.logical_to_next_stripe_offset(w.end)
            will.append(Extent(start, end - start))
            # Head/tail partial stripes overlapping existing data -> read.
            if start < w.offset and start < size:
                read.append(Extent(start, sinfo.stripe_width))
            tail_start = sinfo.logical_to_prev_stripe_offset(w.end)
            if w.end < min(end, size) and tail_start >= start:
                read.append(Extent(tail_start, sinfo.stripe_width))
        plan.will_write[oid] = _merge_extents(will)
        reads = [e for e in _merge_extents(read) if e.off < size]
        if reads:
            plan.to_read[oid] = reads
    return plan


# -- generate ----------------------------------------------------------------

def shard_oid(oid: hobject_t, shard: int,
              generation: int | None = None) -> ghobject_t:
    from .types import NO_GEN
    return ghobject_t(oid, NO_GEN if generation is None else generation,
                      shard)


@dataclass
class PreparedWrite:
    """One stripe-aligned extent whose shard chunks are ready to write."""
    oid: hobject_t
    extent: Extent
    shards: np.ndarray  # (k+m, extent.length / k)


def generate_transactions(
    sinfo: StripeInfo,
    n_shards: int,
    plan: WritePlan,
    txn: PGTransaction,
    encoded: dict[tuple[hobject_t, int], np.ndarray],
    encoded_crcs: dict[tuple[hobject_t, int], list[int]] | None = None,
    gen: int | None = None,
    gen_oids: set[hobject_t] | None = None,
) -> tuple[dict[int, Transaction], dict[hobject_t, HashInfo]]:
    """Turn encoded extents + metadata ops into per-shard Transactions.

    `encoded` maps (oid, extent.off) -> (k+m, chunk_run) shard bytes —
    produced by the backend's batched codec launch.  `encoded_crcs`
    optionally carries cumulative shard crcs the fused TPU kernel
    already produced for an extent (seeded with the prior hinfo state);
    when present for an appending extent the host crc pass is skipped
    entirely.  Returns per-shard transactions and the updated HashInfos
    (written as hinfo xattrs on every shard, reference
    ECTransaction.cc:25-60 encode_and_write).
    """
    encoded_crcs = encoded_crcs or {}
    gen_oids = gen_oids or set()
    txns = {s: Transaction() for s in range(n_shards)}
    new_hinfos: dict[hobject_t, HashInfo] = {}
    for oid, op in txn.ops.items():
        # Object generations (reference ecbackend.rst:9-27 "delete
        # keeps the old generation"): a mutation that cannot be undone
        # by truncation snapshots the shard object under the op's
        # generation id first, making EVERY entry locally rollbackable.
        keep_gen = gen is not None and oid in gen_oids
        if op.delete:
            for s in range(n_shards):
                if keep_gen:
                    txns[s].rename(shard_oid(oid, s),
                                   shard_oid(oid, s, generation=gen))
                else:
                    txns[s].remove(shard_oid(oid, s))
            if not op.writes:
                continue
            # delete-then-recreate: the writes below land on the fresh
            # (vacated) object name — no clone, the rename/remove above
            # already made the generation the rollback snapshot
        elif keep_gen:
            for s in range(n_shards):
                txns[s].clone(shard_oid(oid, s),
                              shard_oid(oid, s, generation=gen))
        hinfo = plan.hash_infos[oid]
        for ext in plan.will_write.get(oid, []):
            shards = encoded[(oid, ext.off)]
            chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(ext.off)
            chunk_run = shards.shape[1]
            appending = chunk_off == hinfo.total_chunk_size
            if appending and (oid, ext.off) in encoded_crcs:
                hinfo.append_precomputed(chunk_off, chunk_run,
                                         encoded_crcs[(oid, ext.off)])
            elif appending:
                hinfo.append(chunk_off, shards)
            else:
                # overwrite inside the object: incremental crc is dead
                # even at unchanged size; the generation kept above
                # carries rollback, the shard chunk_crc carries integrity
                hinfo.invalidate(max(hinfo.total_chunk_size,
                                     chunk_off + chunk_run))
            for s in range(n_shards):
                txns[s].write(shard_oid(oid, s), chunk_off, shards[s])
        if op.truncate_to is not None:
            chunk_size = sinfo.logical_to_next_chunk_offset(op.truncate_to)
            hinfo.truncate(chunk_size)
            for s in range(n_shards):
                txns[s].truncate(shard_oid(oid, s), chunk_size)
        # logical (unpadded) object size, kept in the hinfo xattr
        # (reference: object_info_t.size)
        new_logical = hinfo.logical_size
        for w in op.writes:
            new_logical = max(new_logical, w.end)
        if op.truncate_to is not None:
            new_logical = op.truncate_to
        hinfo.logical_size = new_logical
        if op.attrs:
            sets = {k: v for k, v in op.attrs.items() if v is not None}
            dels = [k for k, v in op.attrs.items() if v is None]
            for s in range(n_shards):
                if sets:
                    txns[s].setattrs(shard_oid(oid, s), sets)
                for k in dels:
                    txns[s].rmattr(shard_oid(oid, s), k)
        # persist hinfo on every shard (xattr hinfo_key, ECUtil.h:101)
        raw = hinfo.encode()
        for s in range(n_shards):
            txns[s].setattr(shard_oid(oid, s), HINFO_KEY, raw)
        new_hinfos[oid] = hinfo
    return txns, new_hinfos
