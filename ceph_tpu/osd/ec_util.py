"""EC stripe geometry + per-shard checksums + stripe-batch codec glue.

Re-expresses reference src/osd/ECUtil.{h,cc}:

* `StripeInfo` — stripe_width/chunk_size arithmetic and logical<->chunk
  offset mapping (reference stripe_info_t, ECUtil.h:27-80).
* `HashInfo` — cumulative per-shard crc32c, persisted as a shard xattr,
  with projected sizes for in-flight ops (reference ECUtil.h:101-160;
  updated by append at ECUtil.cc:172, verified on reads by
  ECBackend::handle_sub_read, checked by deep scrub).
* `encode` / `decode` — slice a logical buffer into stripes and run the
  codec.  TPU-first difference from the reference: where ECUtil::encode
  loops stripes serially calling ec_impl->encode per stripe
  (ECUtil.cc:120-150), here the whole extent (all stripes) goes to the
  codec as ONE batched call — the kernel tiles the byte axis, so more
  stripes just means a longer axis, and cross-transaction batching in
  the backend concatenates further.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..common import crc32c as _crc
from ..ec.interface import ErasureCodeInterface


@dataclass(frozen=True)
class StripeInfo:
    """Geometry of an EC pool's stripes (reference stripe_info_t)."""

    stripe_width: int   # bytes of logical data per stripe (k * chunk_size)
    chunk_size: int     # bytes per shard per stripe

    def __post_init__(self):
        assert self.stripe_width % self.chunk_size == 0, \
            (self.stripe_width, self.chunk_size)

    @property
    def k(self) -> int:
        return self.stripe_width // self.chunk_size

    def logical_to_prev_stripe_offset(self, off: int) -> int:
        return off - off % self.stripe_width

    def logical_to_next_stripe_offset(self, off: int) -> int:
        return -(-off // self.stripe_width) * self.stripe_width

    def logical_to_prev_chunk_offset(self, off: int) -> int:
        """Byte offset within each shard object for a logical offset."""
        return (off // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, off: int) -> int:
        return -(-off // self.stripe_width) * self.chunk_size

    def aligned_logical_offset_to_chunk_offset(self, off: int) -> int:
        assert off % self.stripe_width == 0, off
        return (off // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, off: int) -> int:
        assert off % self.chunk_size == 0, off
        return (off // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, off: int,
                                    length: int) -> tuple[int, int]:
        """Round an extent out to stripe bounds (reference
        stripe_info_t::offset_len_to_stripe_bounds)."""
        start = self.logical_to_prev_stripe_offset(off)
        end = self.logical_to_next_stripe_offset(off + length)
        return start, end - start


HINFO_KEY = "hinfo_key"  # shard xattr name (reference ECUtil.cc get_hinfo_key)
# Per-shard full-chunk crc32c, maintained BY THE SHARD on every write
# once the object's cumulative hinfo is invalidated by an overwrite
# (the integrity story for overwritten objects; the reference's
# allow_ec_overwrites pools lean on deep-scrub reads the same way).
CHUNK_CRC_KEY = "chunk_crc"


def chunk_crc_of(data) -> bytes:
    from ..common import crc32c as _crc32c
    import numpy as _np
    return _crc32c.crc32c(_np.asarray(data).tobytes(),
                          0xFFFFFFFF).to_bytes(4, "little")


def recovery_attrs(hinfo: "HashInfo", data) -> dict[str, bytes]:
    """Xattrs a freshly-rebuilt shard should carry: the hinfo always,
    plus a chunk_crc when the hinfo's cumulative hashes are dead."""
    attrs = {HINFO_KEY: hinfo.encode()}
    if hinfo.invalidated:
        attrs[CHUNK_CRC_KEY] = chunk_crc_of(data)
    return attrs


def refresh_chunk_crcs(store, cid, shard: int, entries) -> None:
    """Shard-side integrity upkeep after applying a sub-write: an
    object that has entered overwrite mode (a generation was kept, or
    a chunk_crc attr already exists from an earlier overwrite) gets
    its full-chunk crc recomputed from local bytes.  Pure appends on
    never-overwritten objects skip this — their cumulative hinfo is
    still authoritative."""
    from .pg_log import LogOp
    from .types import ghobject_t
    seen = set()
    for e in entries:
        if e.op is not LogOp.MODIFY or e.oid in seen:
            continue
        seen.add(e.oid)
        goid = ghobject_t(e.oid, shard=shard)
        if e.rollback.kept_generation is None:
            try:
                store.getattr(cid, goid, CHUNK_CRC_KEY)
            except KeyError:
                continue   # append-only object: hinfo covers it
        try:
            data = store.read(cid, goid)
        except KeyError:
            continue
        from ..store.object_store import Transaction
        txn = Transaction()
        txn.setattr(goid, CHUNK_CRC_KEY, chunk_crc_of(data))
        store.queue_transactions(cid, [txn])


@dataclass
class HashInfo:
    """Cumulative per-shard crc32c + shard/logical sizes.

    Invariant: cumulative_shard_hashes[s] is the crc32c (seed -1) of all
    bytes ever appended to shard s, and total_chunk_size their length.
    Append-only, like the reference (EC overwrites bump object
    generations rather than rewriting ranges in place).

    logical_size carries the object's true byte length (the reference
    keeps this in object_info_t; here it rides the hinfo xattr, which is
    already replicated on every shard) — without it, reads would return
    the stripe-padded size.
    """

    total_chunk_size: int = 0
    cumulative_shard_hashes: list[int] = field(default_factory=list)
    logical_size: int = 0
    # Sticky: once an in-place overwrite/shrink broke the cumulative
    # crcs, later appends fold onto meaningless seeds — the flag must
    # survive so consumers switch to the per-shard chunk_crc attr.
    invalidated: bool = False

    @classmethod
    def make(cls, n_shards: int) -> "HashInfo":
        return cls(0, [0xFFFFFFFF] * n_shards, 0)

    def append(self, old_size: int, shard_chunks: np.ndarray) -> None:
        """Fold one stripe-aligned append into every shard's crc
        (reference HashInfo::append, ECUtil.cc:172).  shard_chunks is
        (n_shards, added_len)."""
        assert old_size == self.total_chunk_size, \
            f"append at {old_size} != current {self.total_chunk_size}"
        n, added = shard_chunks.shape
        assert n == len(self.cumulative_shard_hashes)
        self.cumulative_shard_hashes = _crc.crc32c_rows(
            shard_chunks, self.cumulative_shard_hashes)
        self.total_chunk_size += added

    def append_precomputed(self, old_size: int, added: int,
                           new_hashes: list[int]) -> None:
        """Fold an append whose cumulative crcs were already produced —
        by the fused TPU kernel seeded with the current hashes (the
        north-star single-launch path)."""
        assert old_size == self.total_chunk_size
        assert len(new_hashes) == len(self.cumulative_shard_hashes)
        self.cumulative_shard_hashes = [int(h) & 0xFFFFFFFF
                                        for h in new_hashes]
        self.total_chunk_size += added

    def invalidate(self, new_size: int | None = None) -> None:
        """An in-place change breaks the incremental crcs permanently
        (sticky flag); rollback safety comes from the object generation
        kept at overwrite time, and integrity from the shard-maintained
        chunk_crc attr.  NOTE: a same-size overwrite must invalidate
        too — stale cumulative crcs over new bytes read as corruption."""
        if new_size is not None:
            self.total_chunk_size = new_size
        self.cumulative_shard_hashes = [
            0xFFFFFFFF] * len(self.cumulative_shard_hashes)
        self.invalidated = True

    def truncate(self, new_size: int) -> None:
        if new_size != self.total_chunk_size:
            self.invalidate(new_size)

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    @property
    def crc_valid(self) -> bool:
        """False once an overwrite/shrink broke the cumulative hashes:
        consumers must use the per-shard chunk_crc attr instead."""
        return not self.invalidated and (
            self.total_chunk_size == 0 or
            any(h != 0xFFFFFFFF for h in self.cumulative_shard_hashes))

    # -- persistence (shard xattr) -----------------------------------------

    _MAGIC_V2 = b"HIv2"

    def encode(self) -> bytes:
        import struct
        return self._MAGIC_V2 + struct.pack(
            "<QQII", self.total_chunk_size, self.logical_size,
            1 if self.invalidated else 0,
            len(self.cumulative_shard_hashes)) + b"".join(
            int(h).to_bytes(4, "little")
            for h in self.cumulative_shard_hashes)

    @classmethod
    def decode(cls, raw: bytes) -> "HashInfo":
        import struct
        if raw[:4] == cls._MAGIC_V2:
            size, logical, flags, n = struct.unpack_from("<QQII", raw, 4)
            off = 4 + 24
            inval = bool(flags & 1)
        else:
            # legacy (pre-invalidated-flag) layout: <QQI + hashes
            size, logical, n = struct.unpack_from("<QQI", raw)
            off = 20
            inval = False
        hashes = [int.from_bytes(raw[off + 4 * i:off + 4 + 4 * i],
                                 "little") for i in range(n)]
        return cls(size, hashes, logical, invalidated=inval)


def encode(sinfo: StripeInfo, ec_impl: ErasureCodeInterface,
           data: np.ndarray) -> np.ndarray:
    """Encode a stripe-aligned logical extent into all shard chunks.

    data: (L,) uint8 with L % stripe_width == 0.
    Returns (k+m, L/k): shard s's contiguous bytes for this extent.

    One batched codec call for all stripes: logical layout is
    [stripe0[chunk0..chunkk-1], stripe1[...], ...]; reshaping to
    (nstripes, k, chunk_size) and transposing gives each shard's rows,
    which ride the codec's byte axis in one launch.
    """
    data = np.asarray(data, dtype=np.uint8).ravel()
    assert data.size % sinfo.stripe_width == 0, \
        (data.size, sinfo.stripe_width)
    k = sinfo.k
    m = ec_impl.get_chunk_count() - ec_impl.get_data_chunk_count()
    assert k == ec_impl.get_data_chunk_count()
    nstripes = data.size // sinfo.stripe_width
    # (k, nstripes*chunk_size): row j = shard j's bytes across stripes
    chunks = data.reshape(nstripes, k, sinfo.chunk_size) \
                 .transpose(1, 0, 2).reshape(k, -1)
    parity = np.asarray(ec_impl.encode_chunks(chunks))
    return np.concatenate([chunks, parity], axis=0)


def decode(sinfo: StripeInfo, ec_impl: ErasureCodeInterface,
           shard_data: dict[int, np.ndarray], want_len: int) -> np.ndarray:
    """Rebuild a logical extent from per-shard contiguous buffers
    (reference ECUtil::decode).  shard_data maps shard id -> (chunk-run)
    bytes, all the same length and stripe-aligned."""
    lens = {v.size for v in shard_data.values()}
    assert len(lens) == 1, "mixed shard lengths"
    run = lens.pop()
    assert run % sinfo.chunk_size == 0
    k = sinfo.k
    decoded = ec_impl.decode(set(range(k)),
                             {s: d for s, d in shard_data.items()}, run)
    nstripes = run // sinfo.chunk_size
    stacked = np.stack([np.asarray(decoded[j], dtype=np.uint8)
                        for j in range(k)])        # (k, run)
    logical = stacked.reshape(k, nstripes, sinfo.chunk_size) \
                     .transpose(1, 0, 2).reshape(-1)
    return logical[:want_len]
