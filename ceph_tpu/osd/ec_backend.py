"""ECBackend: the erasure-coded write/read/recovery engine.

Re-expresses reference src/osd/ECBackend.{h,cc} — the north-star
consumer of the TPU codec.  The reference's pipeline:

  submit_transaction (:1483) -> start_rmw (:1839, WritePlan)
  check_ops loop (:2151):
    try_state_to_reads  (:1865)  RMW pre-reads for partial stripes
    try_reads_to_commit (:1939)  encode + per-shard sub-writes
    try_finish_rmw      (:2103)  all shards committed -> client ack,
                                 rollforward bookkeeping

kept stage-for-stage, with the TPU-first twist the whole build exists
for: when try_reads_to_commit drains, EVERY op that is ready encodes in
ONE batched codec launch — the per-stripe loop of ECUtil::encode and the
per-op encode of the reference are hoisted into a single (k, total_run)
kernel call whose byte axis concatenates all extents of all in-flight
transactions (launch-latency amortization; reference analog is the
waiting_reads->waiting_commit queue, which only pipelines, never
batches).

Dispatch-ahead (docs/PIPELINE.md): the drain itself is split into a
submit half (assemble extents, LAUNCH parity+crc, no host sync) and a
completion half (materialize device results, fold crc seeds, issue
sub-writes).  Up to `dispatch_depth` drains stay in flight while more
work is queued or a `pipeline()` window is open, so assembly of drain
N+1 overlaps device compute of drain N; completion always runs in
submit order, and a lone op with nothing behind it still completes
synchronously (the flush-on-idle rule — existing callers see no
change).  The staged device inputs are donated to XLA on real
accelerators (ops/bitsliced submit path).

Shard I/O goes through the ShardBackend seam: LocalShardBackend applies
to a local ObjectStore (the single-process / test topology, like
standalone clusters on MemStore); the messenger-backed implementation
(distribution layer) ships ECSubWrite/ECSubRead messages instead
(reference ECMsgTypes + MOSDECSubOp*).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..common.tracked_op import NULL_TRACKED
from ..ec.interface import ErasureCodeError, ErasureCodeInterface
from ..ops.profiler import device_profiler
from ..parallel.launch_queue import DECODE_MAX_LAUNCH_W
from ..store.object_store import ObjectStore, Transaction
from . import ec_transaction as ect
from . import ec_util
from .ec_transaction import Extent, PGTransaction, WritePlan, shard_oid
from .ec_util import HINFO_KEY, HashInfo, StripeInfo
from .pg_log import LogEntry, LogOp, PGLog, RollbackInfo
from .types import eversion_t, hobject_t, spg_t


# -- shard seam --------------------------------------------------------------

class ShardBackend:
    """Transport seam to one PG's shard replicas (primary's view)."""

    def sub_write(self, shard: int, txn: Transaction,
                  on_commit: Callable[[int], None],
                  log_entries: list | None = None,
                  at_version=None, rollforward_to=None,
                  trace: dict | None = None, top=None) -> None:
        """Apply txn on `shard`; log_entries (pg_log.LogEntry) persist
        atomically with it (reference ECSubWrite.log_entries).  trace
        is an optional child TraceContext wire dict — remote
        transports forward it so the shard holder's sub-op span
        stitches under the primary's op span."""
        raise NotImplementedError

    def sub_read(self, shard: int, oid: hobject_t, off: int, length: int,
                 on_done: Callable[[int, np.ndarray | None], None]) -> None:
        """Read `length` bytes at chunk-offset `off` of oid's shard;
        on_done(shard, data|None-on-error)."""
        raise NotImplementedError

    def sub_read_batch(self, reqs, on_done) -> None:
        """Fan out [(shard, oid, off, length), ...]; transports
        override to amortize per-message scheduling (one reactor task
        for the whole fan-out)."""
        for shard, oid, off, length in reqs:
            self.sub_read(shard, oid, off, length, on_done)

    def get_hinfo(self, shard: int, oid: hobject_t) -> HashInfo | None:
        raise NotImplementedError

    def get_attrs(self, shard: int, oid: hobject_t) -> dict | None:
        """All xattrs of the shard object (hinfo + chunk_crc + user);
        None when the shard object is absent."""
        raise NotImplementedError

    def stat(self, shard: int, oid: hobject_t) -> int | None:
        raise NotImplementedError

    def probe(self, oid: hobject_t, n: int
              ) -> tuple["HashInfo | None", int | None]:
        """One metadata sweep: (hinfo, shard size).  hinfo is
        replicated on every shard, so transports override this to ask
        their LOCAL shard first and the rest in parallel — the
        sequential per-shard fallback here is for local stores."""
        hinfo = None
        size = None
        for s in range(n):
            if hinfo is None:
                hinfo = self.get_hinfo(s, oid)
                if hinfo is not None:
                    return hinfo, size
            if size is None:
                size = self.stat(s, oid)
        return hinfo, size


class LocalShardBackend(ShardBackend):
    """All shards in one local ObjectStore, per-shard collections —
    the MemStore test topology (and the per-OSD local shard path of
    handle_sub_write, reference ECBackend.cc:2086)."""

    def __init__(self, store: ObjectStore, pgid, n_shards: int):
        from .pg_log import ShardPGLog
        self.store = store
        self.n_shards = n_shards
        self.cids = {s: spg_t(pgid, s) for s in range(n_shards)}
        for cid in self.cids.values():
            store.create_collection(cid)
        self.shard_logs = {s: ShardPGLog(store, self.cids[s], s)
                           for s in range(n_shards)}

    def sub_write(self, shard, txn, on_commit, log_entries=None,
                  at_version=None, rollforward_to=None, trace=None,
                  top=None):
        # top: tracked op for wire-plane trace stitching — local
        # shards have no wire, so it is accepted and unused here
        slog = self.shard_logs[shard]
        if log_entries and at_version is not None:
            slog.append_to_txn(txn, log_entries, at_version)
        self.store.queue_transactions(self.cids[shard], [txn])
        if log_entries:
            slog.record(log_entries, at_version)
            ec_util.refresh_chunk_crcs(self.store, self.cids[shard],
                                       shard, log_entries)
        if rollforward_to is not None:
            slog.advance_rollforward(rollforward_to)
        on_commit(shard)

    def sub_read(self, shard, oid, off, length, on_done):
        goid = shard_oid(oid, shard)
        try:
            data = self.store.read(self.cids[shard], goid, off, length)
        except KeyError:
            on_done(shard, None)
            return
        if data.size < length:  # pad short reads (sparse tail)
            data = np.concatenate(
                [data, np.zeros(length - data.size, dtype=np.uint8)])
        on_done(shard, data)

    def get_hinfo(self, shard, oid):
        goid = shard_oid(oid, shard)
        try:
            raw = self.store.getattr(self.cids[shard], goid, HINFO_KEY)
        except KeyError:
            return None
        return HashInfo.decode(raw)

    def get_attrs(self, shard, oid):
        try:
            return self.store.getattrs(self.cids[shard],
                                       shard_oid(oid, shard))
        except KeyError:
            return None

    def stat(self, shard, oid):
        try:
            return self.store.stat(self.cids[shard], shard_oid(oid, shard))
        except KeyError:
            return None


# -- pipeline op -------------------------------------------------------------

@dataclass
class ECOp:
    """An in-flight client transaction (reference ECBackend::Op)."""
    txn: PGTransaction
    version: eversion_t
    on_commit: Callable[[], None]
    plan: WritePlan | None = None
    # metadata prefetched OUTSIDE the pipeline lock (oid -> probe
    # result): the probe is a blocking RPC fan-out, and running it
    # under be.lock starves every other op AND the dispatch threads
    # that must deliver its replies
    meta: dict = field(default_factory=dict)
    pending_reads: int = 0
    read_data: dict[tuple[hobject_t, int], np.ndarray] = field(
        default_factory=dict)
    pending_commits: int = 0
    state: str = "queued"
    error: Exception | None = None
    # extents this op actually pinned in the ExtentCache (populated
    # incrementally during assembly): release must mirror EXACTLY the
    # present() calls — releasing the full plan after a mid-assembly
    # failure would decrement another in-flight op's pin on the same
    # range and let stale store bytes satisfy a later overlay
    pinned: list[tuple[hobject_t, int, int]] = field(default_factory=list)
    # per-op trace/timeline (common/tracked_op.py); NULL_TRACKED when
    # tracking is off — every mark_event below is then a no-op
    top: object = NULL_TRACKED


@dataclass
class _Drain:
    """One submitted (launched, not yet materialized) pipeline drain."""
    ops: list[ECOp]
    # (op, oid, extent, run (k, W)) per stripe-aligned extent, op order
    work: list[tuple]
    kinds: list[str]                  # per work item: "fused" | "plain"
    fused_handle: object | None       # plugin submit handle
    fused_pos: dict[int, int]         # work index -> position in handle
    plain_handle: tuple | None        # ("mesh"|"plugin"|"np", handle)
    plain_cols: dict[int, int]        # work index -> column offset
    t_assemble: float = 0.0
    # flight-recorder records of DIRECT (non-queue) launches; queue
    # launches are recorded by the queue itself and stitched back
    # through the ticket's launch_id (ops/profiler.py)
    prof_fused: object | None = None
    prof_plain: object | None = None


def _build_ec_perf(name: str):
    """The backend's own counter set (registered into the daemon's
    PerfCountersCollection so `perf dump` and the prometheus exporter
    surface it)."""
    from ..common.perf_counters import PerfCountersBuilder
    return (PerfCountersBuilder(name)
            .add_u64_counter("ec_drain_submits", "pipeline drains launched")
            .add_u64_counter("ec_drain_extents", "extents encoded")
            .add_u64_counter("ec_drain_errors",
                             "sub-write/encode failures absorbed")
            .add_gauge("ec_inflight_depth",
                       "drains in flight after last submit")
            .add_time_avg("ec_drain_assemble",
                          "host assemble+launch time per drain")
            .add_time_avg("ec_drain_device",
                          "device materialize (block) time per drain")
            .add_time_avg("ec_drain_commit",
                          "sub-write issue time per drain")
            .add_u64_counter("ec_fused_kernel_drains",
                             "fused drains served by the hier kernels")
            .add_u64_counter("ec_fused_fallback_drains",
                             "fused drains served by a fallback path")
            .add_u64_counter("ec_host_queue_drains",
                             "drains routed through the per-host "
                             "launch queue (cross-PG batching)")
            .add_u64_counter("ec_scrub_device_bytes",
                             "deep-scrub bytes crc'd on device")
            .add_u64_counter("ec_scrub_host_bytes",
                             "deep-scrub bytes crc'd on host")
            .add_u64_counter("ec_mesh_drains",
                             "drains dispatched to the mesh plane")
            .add_u64_counter("ec_mesh_repair_launches",
                             "batched distributed repair decodes")
            .add_u64_counter("ec_mesh_errors",
                             "mesh launch failures (plane fell back)")
            # repair subsystem (docs/REPAIR.md): the CLAY savings made
            # visible — helper bytes actually read vs bytes rebuilt —
            # plus the degraded-read path's provenance
            .add_u64_counter("ec_repair_helper_bytes",
                             "survivor/helper bytes read for repair")
            .add_u64_counter("ec_repair_reconstructed_bytes",
                             "shard bytes rebuilt by repair decodes")
            .add_u64_counter("ec_clay_repairs",
                             "objects repaired from repair-plane reads "
                             "(bandwidth-optimal CLAY path)")
            .add_u64_counter("ec_clay_repair_launches",
                             "batched CLAY repair-plan launches")
            .add_u64_counter("ec_clay_repair_fallbacks",
                             "CLAY plane-read repairs that fell back "
                             "to the full-read decode path")
            .add_u64_counter("ec_reconstruct_reads",
                             "degraded client reads served by "
                             "reconstruct-on-read")
            .add_u64_counter("ec_reconstruct_read_bytes",
                             "logical bytes served by "
                             "reconstruct-on-read")
            .add_u64_counter("ec_read_timeouts",
                             "client-read shard fan-outs that hit "
                             "osd_ec_read_timeout")
            .create_perf_counters())


class ECBackend:
    def __init__(self, ec_impl: ErasureCodeInterface, sinfo: StripeInfo,
                 shards: ShardBackend, log: PGLog | None = None,
                 mesh_codec=None, mesh_service=None,
                 launch_queue=None, dispatch_depth: int = 2,
                 perf=None, perf_name: str = "ec", logger=None,
                 read_timeout: float = 30.0,
                 clay_repair: bool = True):
        self.ec_impl = ec_impl
        self.sinfo = sinfo
        self.shards = shards
        self.k = ec_impl.get_data_chunk_count()
        self.m = ec_impl.get_coding_chunk_count()
        self.n = ec_impl.get_chunk_count()
        assert sinfo.k == self.k
        self._logger = logger
        # Optional multi-chip data plane (parallel.DistributedStripeCodec):
        # when set, batched drains and repair decodes dispatch to the
        # sharded collective program instead of the single-chip codec.
        # Acquired from the per-host MeshService when one is supplied
        # (the deployment path, docs/MULTICHIP.md); a directly-injected
        # codec (tests, benches) takes precedence.  Geometry/matrix
        # mismatches are CONFIG errors, not crashes: the backend logs,
        # records mesh_error, and serves from the single-chip plane —
        # a mis-provisioned mesh must never take an OSD down with it.
        self.mesh_error: str | None = None
        self._mesh_service = mesh_service
        if mesh_codec is None and mesh_service is not None:
            impl_matrix = getattr(ec_impl, "matrix", None)
            if impl_matrix is None:
                # no generator matrix to validate against (bitmatrix-
                # only or layered codes): an unvalidated mesh codec
                # could silently write divergent parity — refuse it
                self._mesh_config_error(
                    "plugin exposes no generator matrix to validate "
                    "against the mesh codec")
            else:
                try:
                    mesh_codec = mesh_service.acquire(
                        self.k, self.m,
                        technique=getattr(ec_impl, "technique",
                                          "cauchy"),
                        matrix=impl_matrix)
                except Exception as e:  # noqa: BLE001 — MeshError et al
                    self._mesh_config_error(f"mesh acquire failed: {e}")
                    mesh_codec = None
        if mesh_codec is not None:
            why = self._mesh_geometry_error(mesh_codec)
            if why is not None:
                self._mesh_config_error(why)
                mesh_codec = None
        self.mesh_codec = mesh_codec
        # Per-host EC launch queue (parallel/launch_queue.py): when
        # set, this backend's drains submit their encode runs to the
        # shared queue — which coalesces them with OTHER PGs' runs
        # into one super-batch launch per window — instead of issuing
        # a private partial-occupancy launch.  Completion, in-order
        # acks, and failure containment stay per-PG; the queue only
        # owns the launch.
        self._launch_queue = launch_queue
        # degraded-read fan-out wait (conf osd_ec_read_timeout): was a
        # hardcoded 30 s; timeouts now count (ec_read_timeouts) instead
        # of silently shaping latency
        self.read_timeout = max(0.05, float(read_timeout))
        # CLAY plane-read repair (docs/REPAIR.md): when the plugin is
        # sub-chunked with a repair lowering, single-shard recovery
        # reads only the repair planes of d helpers and rebuilds via a
        # batched GF matmul; off = always full-read decode
        self._clay_repair = bool(clay_repair)
        self._clay_plans: dict[tuple, object] = {}
        self.log = log or PGLog()
        self.lock = threading.RLock()
        self.waiting_state: list[ECOp] = []
        self.waiting_reads: list[ECOp] = []
        self.waiting_commit: list[ECOp] = []
        self.completed: int = 0
        self.batched_launches: int = 0
        self.batched_extents: int = 0
        # kernel path of the last fused drain ("hier_acc"/"hier_lsub"/
        # "w32_flat"/"bytes"/"xla"; None before the first fused drain)
        self.fused_path: str | None = None
        self._hold = 0
        # dispatch-ahead pipeline (docs/PIPELINE.md): submitted drains
        # whose device work is in flight, completion in submit order
        self.dispatch_depth = max(1, int(dispatch_depth))
        self.perf = perf if perf is not None else _build_ec_perf(perf_name)
        from collections import deque
        self._inflight: "deque[_Drain]" = deque()
        self._pipeline_win = 0        # pipeline() windows currently open
        self._completing = False      # re-entrancy guard for completion
        self._auto_flush_ms: float | None = None
        self._flush_timer = None
        # projected end-of-chunk per object across IN-FLIGHT drains:
        # the submit-time append/fused decision for drain N+1 must see
        # the sizes drain N will produce, which the (shared) projected
        # hinfo only reflects after N's completion stage runs
        self._sim_chunk: dict[hobject_t, int] = {}
        self._sim_refs: dict[hobject_t, int] = {}
        from .extent_cache import ExtentCache
        self.extent_cache = ExtentCache()
        # projected per-object state for queued-but-uncommitted ops
        # (reference HashInfo "projected sizes for in-flight ops",
        # ECUtil.h:101-160): later ops in the pipeline plan against the
        # in-flight hinfo instance, not the stored one.
        self._projected: dict[hobject_t, dict] = {}

    # -- mesh plane management (docs/MULTICHIP.md) --------------------------

    def _log(self, msg: str) -> None:
        if self._logger is not None:
            self._logger(msg)
        else:
            from ..common.dout import dout
            dout("ec", 1, msg)

    def _mesh_geometry_error(self, mesh_codec) -> str | None:
        """Why `mesh_codec` cannot serve this backend (None = it can).
        These were startup asserts once; a geometry/matrix mismatch is
        an operator config error and must fall back, not crash."""
        if (mesh_codec.k, mesh_codec.m) != (self.k, self.m):
            return (f"mesh codec geometry k={mesh_codec.k} "
                    f"m={mesh_codec.m} does not match the EC profile "
                    f"k={self.k} m={self.m}")
        # technique must match too: cauchy parity written by the mesh
        # is garbage to a reed_sol_van plugin's decode matrix
        impl_matrix = getattr(self.ec_impl, "matrix", None)
        if impl_matrix is not None and \
                not np.array_equal(mesh_codec.matrix, impl_matrix):
            return ("mesh codec generator matrix does not match the "
                    "plugin's — mesh parity would not decode on the "
                    "single-chip plane")
        return None

    def _mesh_config_error(self, why: str) -> None:
        self.mesh_error = why
        self._log(f"EC mesh plane unavailable ({why}); "
                  f"serving from the single-chip codec")

    def _disable_mesh(self, err: BaseException) -> None:
        """Containment: a failed mesh launch aborts its op (the caller
        does that); HERE the backend permanently falls back to the
        single-chip plane so subsequent drains/repairs never touch the
        broken mesh — the queue must not wedge retrying a dead device.
        Reported to the MeshService ledger for `mesh status`."""
        if self.mesh_codec is None:
            return
        # keep a reference for drains already in flight on the mesh:
        # their device futures may be healthy even though new work
        # must not be dispatched there
        self._mesh_fallen = self.mesh_codec
        self.mesh_codec = None
        self.mesh_error = f"mesh plane disabled after failure: {err!r}"
        self._log(self.mesh_error)
        if self.perf:
            self.perf.inc("ec_mesh_errors")
        if self._mesh_service is not None:
            self._mesh_service.note_failure(err)

    def _note_fused_path(self, path: str | None) -> None:
        """Record which fused kernel family served a drain (hier_* =
        the overlapped Pallas kernels, anything else a fallback).
        Direct submits attribute at launch; launch-queue drains at
        completion (the super-batch's path is unknown until the
        shared launch fires)."""
        self.fused_path = path
        if self.perf:
            self.perf.inc(
                "ec_fused_kernel_drains"
                if path and path.startswith("hier")
                else "ec_fused_fallback_drains")

    def repair_status(self) -> dict:
        """Per-PG repair state (surfaced by the OSD's `repair status`
        asok, docs/REPAIR.md): the helper-bytes-read vs
        reconstructed-bytes ledger — the CLAY savings made visible —
        plus reconstruct-on-read and read-timeout provenance."""
        dump = self.perf.dump() if self.perf else {}

        def u64(key):
            v = dump.get(key, 0)
            return int(v) if isinstance(v, (int, float)) else 0
        helper = u64("ec_repair_helper_bytes")
        rebuilt = u64("ec_repair_reconstructed_bytes")
        return {
            "helper_bytes_read": helper,
            "reconstructed_bytes": rebuilt,
            "helper_bytes_per_rebuilt": round(helper / rebuilt, 3)
            if rebuilt else None,
            "clay_repairs": u64("ec_clay_repairs"),
            "clay_repair_launches": u64("ec_clay_repair_launches"),
            "clay_repair_fallbacks": u64("ec_clay_repair_fallbacks"),
            "clay_plans_cached": len(self._clay_plans),
            "mesh_repair_launches": u64("ec_mesh_repair_launches"),
            "reconstruct_reads": u64("ec_reconstruct_reads"),
            "reconstruct_read_bytes": u64("ec_reconstruct_read_bytes"),
            "read_timeouts": u64("ec_read_timeouts"),
            "read_timeout_s": self.read_timeout,
            "clay_plane_repair": self._clay_repair,
        }

    def mesh_status(self) -> dict:
        """Per-backend plane state (surfaced by the OSD's
        `mesh status` asok)."""
        mc = self.mesh_codec
        return {
            "active": mc is not None,
            "mesh": ({"shard": mc.n_shard, "data": mc.n_data}
                     if mc is not None else None),
            "error": self.mesh_error,
        }

    def batch(self):
        """Batch window: ops submitted inside encode in one codec launch.

        The explicit form of the pipeline's natural batching: with async
        shard I/O, ops pile up in waiting_reads while earlier launches
        are in flight and drain together; with synchronous stores (tests,
        single-process) this context manager provides the same window
        (the `BlueStore deferred`-style dynamic batch window named in
        SURVEY.md section 7 hard parts).
        """
        import contextlib

        @contextlib.contextmanager
        def _win():
            with self.lock:
                self._hold += 1
            try:
                yield
            finally:
                with self.lock:
                    self._hold -= 1
                    if self._hold == 0:
                        self.check_ops()
        return _win()

    def pipeline(self):
        """Dispatch-ahead window: while open, up to `dispatch_depth`
        drains stay in flight on the device (submit of drain N+1
        overlaps compute of drain N); everything flushes — completing
        in submit order — when the window closes.  Unlike batch()
        (which HOLDS ops to coalesce them into one launch), ops drain
        immediately here; only materialization is deferred."""
        import contextlib

        @contextlib.contextmanager
        def _win():
            with self.lock:
                self._pipeline_win += 1
            try:
                yield
            finally:
                with self.lock:
                    self._pipeline_win -= 1
                    if self._pipeline_win == 0:
                        self.flush_pipeline()
        return _win()

    def set_pipelined(self, flush_ms: float = 2.0) -> None:
        """Persistent dispatch-ahead (daemon mode): the window never
        closes, so a flush timer bounds the commit latency of the last
        drains when the op stream goes idle."""
        with self.lock:
            self._pipeline_win += 1
            self._auto_flush_ms = max(0.1, float(flush_ms))

    def flush_pipeline(self) -> None:
        """Complete every in-flight drain, in submit order."""
        with self.lock:
            if self._completing:
                return
            self._completing = True
            try:
                while self._inflight:
                    self._complete_drain(self._inflight.popleft())
            finally:
                self._completing = False
            if self.perf:
                self.perf.set("ec_inflight_depth", 0)

    def _arm_auto_flush(self) -> None:
        if self._auto_flush_ms is None or self._flush_timer is not None:
            return

        def _fire():
            with self.lock:
                self._flush_timer = None
            self.flush_pipeline()

        t = threading.Timer(self._auto_flush_ms / 1000.0, _fire)
        t.daemon = True
        self._flush_timer = t
        t.start()

    def inflight_ops(self) -> list[ECOp]:
        """Ops submitted to the device pipeline, not yet committing
        (for dump_ops_in_flight)."""
        with self.lock:
            return [op for d in self._inflight for op in d.ops]

    # -- object metadata helpers -------------------------------------------

    def _fetch_hinfo(self, oid: hobject_t) -> HashInfo | None:
        """hinfo is replicated on every shard; one probe sweep (local
        shard first, rest in parallel — see ShardBackend.probe)."""
        return self.shards.probe(oid, self.n)[0]

    def _get_hinfo(self, oid: hobject_t) -> HashInfo:
        return self._fetch_hinfo(oid) or HashInfo.make(self.n)

    def _get_size(self, oid: hobject_t) -> int:
        """True (unpadded) object size from the hinfo xattr; falls back
        to the stripe-derived size for objects without one."""
        hinfo, chunk = self.shards.probe(oid, self.n)
        if hinfo is not None:
            return hinfo.logical_size
        if chunk is not None:
            return self.sinfo.aligned_chunk_offset_to_logical_offset(
                chunk)
        return 0

    def exists(self, oid: hobject_t) -> bool:
        hinfo, chunk = self.shards.probe(oid, self.n)
        return hinfo is not None or chunk is not None

    # -- entry (reference submit_transaction :1483 / start_rmw :1839) ------

    def make_op(self, txn: PGTransaction,
                on_commit: Callable[[], None], top=None) -> ECOp:
        """Stage an op WITHOUT entering the pipeline: prefetches object
        metadata (a blocking RPC fan-out) so no lock is held during it.
        The racy peek at _projected is benign: the plan re-checks it
        under the lock and falls back to a locked probe on a miss."""
        op = ECOp(txn, eversion_t(), on_commit,
                  top=top if top is not None else NULL_TRACKED)
        for oid in txn.ops:
            if oid not in self._projected:
                op.meta[oid] = self.shards.probe(oid, self.n)
        return op

    def enqueue(self, op: ECOp, version: eversion_t) -> ECOp:
        """Enter the pipeline; the caller serializes version allocation
        with this call (versions must enter the FIFO in order)."""
        op.version = version
        with self.lock:
            self.waiting_state.append(op)
            self.check_ops()
        return op

    def submit_transaction(self, txn: PGTransaction, version: eversion_t,
                           on_commit: Callable[[], None],
                           top=None) -> ECOp:
        return self.enqueue(self.make_op(txn, on_commit, top=top),
                            version)

    # -- pipeline (reference check_ops :2151) -------------------------------

    def check_ops(self) -> None:
        if self._hold:
            return
        self._try_state_to_reads()
        self._try_reads_to_commit()
        # (try_finish_rmw runs from the sub-write callbacks)

    def _try_state_to_reads(self) -> None:
        while self.waiting_state:
            op = self.waiting_state[0]
            # One hinfo fetch sweep per object: the plan needs both the
            # hinfo and the size, and size is derived from hinfo when it
            # exists (over the messenger each shard fetch is a blocking
            # RPC, so the sweep count matters).
            cache: dict = {}

            def fetch(oid):
                """(hinfo|None, shard_size|None): projected (in-flight)
                state first, then the op's prefetched probe, then (rare
                race fallback) a probe under the lock."""
                proj = self._projected.get(oid)
                if proj is not None:
                    return proj["hinfo"], None
                if oid in op.meta:
                    return op.meta[oid]
                if oid not in cache:
                    cache[oid] = self.shards.probe(oid, self.n)
                return cache[oid]

            def get_hinfo(oid):
                h, _sz = fetch(oid)
                if h is None:
                    h = HashInfo.make(self.n)
                # later queued ops must chain off this same instance
                proj = self._projected.setdefault(
                    oid, {"hinfo": h, "refs": 0})
                proj["refs"] += 1
                return proj["hinfo"]

            def get_size(oid):
                h, chunk = fetch(oid)
                if h is not None:
                    return h.logical_size
                if chunk is not None:
                    return (self.sinfo
                            .aligned_chunk_offset_to_logical_offset(
                                chunk))
                return 0

            def reset_hinfo(oid):
                """Delete-then-recreate: swap a FRESH hinfo into the
                projected chain so THIS op and later queued ops seed
                from the recreate, while earlier in-flight ops keep
                folding onto the instance they planned against (refs
                bookkeeping rides the same cache entry)."""
                h = HashInfo.make(self.n)
                proj = self._projected.get(oid)
                if proj is not None:
                    proj["hinfo"] = h
                return h

            op.plan = ect.get_write_plan(
                self.sinfo, op.txn, get_hinfo, get_size,
                reset_hinfo=reset_hinfo)
            self.waiting_state.pop(0)
            op.state = "reading"
            self.waiting_reads.append(op)
            reads = []
            for oid, extents in op.plan.to_read.items():
                for e in extents:
                    reads.append((oid, e))
            op.pending_reads = len(reads)
            for oid, e in reads:
                self._start_rmw_read(op, oid, e)

    def _start_rmw_read(self, op: ECOp, oid: hobject_t, e: Extent) -> None:
        """Read one stripe-aligned logical extent back from the data
        shards (degraded shards reconstruct via decode)."""
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(e.off)
        chunk_len = e.length // self.k
        got: dict[int, np.ndarray] = {}
        failed: set[int] = set()

        def on_done(shard: int, data: np.ndarray | None) -> None:
            if data is None:
                failed.add(shard)
            else:
                got[shard] = data
            if len(got) + len(failed) == self.k and not failed:
                logical = ec_util.decode(
                    self.sinfo, self.ec_impl, got, e.length)
                self._rmw_read_complete(op, oid, e, logical)
            elif failed and len(got) < self.k:
                self._read_with_reconstruct(op, oid, e, chunk_off,
                                            chunk_len, got, failed)

        for s in range(self.k):
            self.shards.sub_read(s, oid, chunk_off, chunk_len, on_done)

    def _read_with_reconstruct(self, op, oid, e, chunk_off, chunk_len,
                               got, failed) -> None:
        """Degraded pre-read: pull parity shards until k available
        (reference objects_read_and_reconstruct :2345 +
        get_remaining_shards :1633)."""
        tried = set(got) | set(failed)
        candidates = [s for s in range(self.n) if s not in tried]

        def on_done(shard, data):
            if data is not None:
                got[shard] = data
            if len(got) >= self.k:
                logical = ec_util.decode(
                    self.sinfo, self.ec_impl,
                    dict(list(got.items())[: self.k] if len(got) > self.k
                         else got), e.length)
                self._rmw_read_complete(op, oid, e, logical)

        if len(candidates) + len(got) < self.k:
            raise ErasureCodeError(5, f"unrecoverable: {oid} extent {e}")
        for s in candidates[: self.k - len(got)]:
            self.shards.sub_read(s, oid, chunk_off, chunk_len, on_done)

    def _rmw_read_complete(self, op, oid, e, logical) -> None:
        with self.lock:
            op.read_data[(oid, e.off)] = logical
            op.pending_reads -= 1
            if op.pending_reads == 0:
                self._try_reads_to_commit()

    # -- encode + commit (reference try_reads_to_commit :1939) --------------

    def _assemble_extent(self, op: ECOp, oid: hobject_t,
                         e: Extent) -> np.ndarray:
        """Overlay new writes on pre-read/zero background for one
        stripe-aligned extent."""
        buf = np.zeros(e.length, dtype=np.uint8)
        rd = op.read_data.get((oid, e.off))
        if rd is not None:
            buf[: rd.size] = rd
        else:
            # partial overlap with other read extents
            for (roid, roff), data in op.read_data.items():
                if roid != oid:
                    continue
                lo = max(e.off, roff)
                hi = min(e.end, roff + data.size)
                if lo < hi:
                    buf[lo - e.off:hi - e.off] = data[lo - roff:hi - roff]
        # bytes assembled by earlier in-flight ops win over store reads
        self.extent_cache.overlay(oid, e.off, buf)
        for w in op.txn.ops[oid].writes:
            lo = max(e.off, w.offset)
            hi = min(e.end, w.end)
            if lo < hi:
                buf[lo - e.off:hi - e.off] = w.data[lo - w.offset:hi - w.offset]
        return buf

    def _try_reads_to_commit(self) -> None:
        ready: list[ECOp] = []
        while self.waiting_reads and self.waiting_reads[0].pending_reads == 0:
            ready.append(self.waiting_reads.pop(0))
        if ready:
            try:
                drain = self._submit_drain(ready)
            except Exception as e:  # noqa: BLE001 — encode staging died
                # complete earlier in-flight drains FIRST so their acks
                # (lower versions) precede these ops' error acks —
                # completion stays in submit order even on failure
                self.flush_pipeline()
                for op in ready:
                    self._abort_op(op, e)
            else:
                self._inflight.append(drain)
                if self.perf:
                    self.perf.inc("ec_drain_submits")
                    self.perf.set("ec_inflight_depth", len(self._inflight))
                self._arm_auto_flush()
        self._drain_pipeline()

    # -- submit half: assemble + launch, NO host sync -----------------------

    def _submit_drain(self, ready: list[ECOp]) -> _Drain:
        """Gather every extent of every ready op, encode the whole
        drain with launches that return device futures (one fused
        launch for appends + one plain launch for overwrites), and
        record the in-flight drain.  Nothing here blocks on the
        device; materialization happens in _complete_drain."""
        import time as _time
        t0 = _time.perf_counter()
        k = self.k
        work: list[tuple] = []
        runs: list[np.ndarray] = []
        for op in ready:
            op.state = "encoding"
            for oid, extents in op.plan.will_write.items():
                for e in extents:
                    buf = self._assemble_extent(op, oid, e)
                    # pin so later ops in this (or the next) drain see
                    # these bytes instead of stale store reads
                    self.extent_cache.present(oid, e.off, buf)
                    op.pinned.append((oid, e.off, e.length))
                    nstripes = e.length // self.sinfo.stripe_width
                    work.append((op, oid, e, buf))
                    runs.append(buf.reshape(
                        nstripes, k, self.sinfo.chunk_size)
                        .transpose(1, 0, 2).reshape(k, -1))
        drain = _Drain(ops=ready, work=work, kinds=[],
                       fused_handle=None, fused_pos={},
                       plain_handle=None, plain_cols={})
        if not work:
            # no encode work: no launch/materialize events — a
            # fabricated launch would poison per-stage blame and the
            # lat_ec_encode_launch histogram
            return drain
        # North-star fused path: every chunk-aligned appending extent
        # of the WHOLE drain gets parity + cumulative shard crcs from
        # one kernel launch.  The append decision uses _sim_chunk, the
        # projected end-of-chunk across ALL in-flight drains (the
        # shared hinfo instances only advance at completion).  Non-
        # append extents (overwrites) take the plain parity path: their
        # incremental crc is invalidated anyway (generations work).
        fused_idx: list[int] = []
        plain_idx: list[int] = []
        can_fuse = self.mesh_codec is None and \
            hasattr(self.ec_impl, "encode_extents_with_crc_submit")
        deleted: set[tuple[int, hobject_t]] = set()
        for i, ((op, oid, e, _), run) in enumerate(zip(work, runs)):
            hinfo = op.plan.hash_infos[oid]
            if op.txn.ops[oid].delete and (id(op), oid) not in deleted:
                # delete-then-recreate: the fresh plan hinfo starts at 0
                deleted.add((id(op), oid))
                self._sim_chunk[oid] = 0
            cur = self._sim_chunk.get(oid, hinfo.total_chunk_size)
            chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(
                e.off)
            if can_fuse and chunk_off == cur:
                fused_idx.append(i)
                self._sim_chunk[oid] = cur + run.shape[1]
            else:
                plain_idx.append(i)
                self._sim_chunk[oid] = max(cur, chunk_off + run.shape[1])
            self._sim_refs[oid] = self._sim_refs.get(oid, 0) + 1
        # txn-level size effects that land after the writes (mirrors
        # generate_transactions order): truncate clamps the projection.
        # Only for objects this drain TRACKS (has a _sim_refs entry
        # from a work item) — an untracked entry would never be
        # released by _drop_sim_refs and the stale projection would
        # push all later appends off the fused path; pure truncates
        # stay safe via generate's own append re-check
        for op in ready:
            for oid, objop in op.txn.ops.items():
                if objop.truncate_to is not None and \
                        oid in self._sim_refs:
                    self._sim_chunk[oid] = \
                        self.sinfo.logical_to_next_chunk_offset(
                            objop.truncate_to)
        fused_set = set(fused_idx)
        drain.kinds = ["fused" if i in fused_set else "plain"
                       for i in range(len(work))]
        # flight recorder (ops/profiler.py): direct launches record
        # here; queue submissions carry the ops' trace ids so the
        # queue's super-batch record can name its contributors
        from ..parallel.launch_queue import (_codec_label,
                                             _extents_bucket)
        prof = device_profiler()
        traces = tuple(op.top.trace.trace_id for op in ready
                       if op.top.is_tracked) if prof.enabled else ()
        try:
            if fused_idx:
                drain.fused_pos = {wi: p
                                   for p, wi in enumerate(fused_idx)}
                fused_runs = [runs[i] for i in fused_idx]
                if self._launch_queue is not None:
                    # per-host continuous batching: the queue
                    # coalesces these runs with other PGs' into one
                    # super-batch launch; kernel-path attribution
                    # waits for the launch (completion half)
                    drain.fused_handle = \
                        self._launch_queue.submit_extents(
                            self.ec_impl, fused_runs, owner=id(self),
                            traces=traces)
                    if self.perf:
                        self.perf.inc("ec_host_queue_drains")
                else:
                    rec = prof.begin(
                        "fused_encode", codec=_codec_label(self.ec_impl),
                        runs=len(fused_runs),
                        nbytes=sum(r.size for r in fused_runs),
                        traces=traces)
                    drain.fused_handle = \
                        self.ec_impl.encode_extents_with_crc_submit(
                            fused_runs)
                    prof.submitted(
                        rec,
                        self.ec_impl.launch_bucket(drain.fused_handle)
                        if hasattr(self.ec_impl, "launch_bucket")
                        else _extents_bucket(drain.fused_handle),
                        path=drain.fused_handle.get("path")
                        if isinstance(drain.fused_handle, dict)
                        else None)
                    drain.prof_fused = rec
                    # kernel-path provenance (ISSUE 11): which fused
                    # kernel served this drain — hier_acc/hier_lsub
                    # are the overlapped Pallas family, anything else
                    # is a fallback; surfaced as perf counters +
                    # fused_path so a silent fallback at plugin init
                    # is attributable from `perf dump`, not just a
                    # slower bench row
                    self._note_fused_path(
                        drain.fused_handle.get("path")
                        if isinstance(drain.fused_handle, dict)
                        else None)
            if plain_idx:
                col = 0
                for i in plain_idx:
                    drain.plain_cols[i] = col
                    col += runs[i].shape[1]
                plain_runs = [runs[i] for i in plain_idx]
                big = np.concatenate(plain_runs, axis=1) \
                    if len(plain_runs) > 1 else plain_runs[0]
                if self.mesh_codec is not None:
                    rec = prof.begin(
                        "mesh_encode", codec=_codec_label(self.ec_impl),
                        nbytes=int(big.size), traces=traces)
                    try:
                        drain.plain_handle = (
                            "mesh",
                            self.mesh_codec.encode_flat_submit(big))
                    except Exception as e:  # noqa: BLE001 — mesh died
                        # containment: this drain's ops abort (outer
                        # handler), later drains take the single-chip
                        # plane — the mesh never wedges the queue
                        self._disable_mesh(e)
                        raise
                    prof.submitted(rec, f"mesh:x:w{big.shape[1]}",
                                   path="mesh")
                    drain.prof_plain = rec
                    if self.perf:
                        self.perf.inc("ec_mesh_drains")
                elif self._launch_queue is not None:
                    drain.plain_handle = (
                        "queue", self._launch_queue.submit_chunks(
                            self.ec_impl, big, owner=id(self),
                            traces=traces))
                    if self.perf and not fused_idx:
                        self.perf.inc("ec_host_queue_drains")
                elif hasattr(self.ec_impl, "encode_chunks_submit"):
                    rec = prof.begin(
                        "plain_encode", codec=_codec_label(self.ec_impl),
                        nbytes=int(big.size), traces=traces)
                    h = self.ec_impl.encode_chunks_submit(big)
                    drain.plain_handle = ("plugin", h)
                    prof.submitted(rec, f"c:{h[0]}:w{big.shape[1]}",
                                   path=str(h[0]))
                    drain.prof_plain = rec
                else:
                    # host-synchronous CPU plugins: nothing to defer —
                    # the whole launch is the submit; device time 0
                    rec = prof.begin(
                        "plain_encode", codec=_codec_label(self.ec_impl),
                        nbytes=int(big.size), traces=traces)
                    drain.plain_handle = (
                        "np", np.asarray(self.ec_impl.encode_chunks(big)))
                    # jit=False: a pure-CPU encode has no compiled
                    # program — its wall must not read as a "compile"
                    prof.submitted(rec, f"c:np:w{big.shape[1]}",
                                   path="np",
                                   jit=getattr(self.ec_impl,
                                               "jit_backed", False))
                    prof.materialized(rec, 0.0)
        except Exception:
            # withdraw any queue submissions this drain already made:
            # the owning ops are about to abort, and an orphaned
            # pending submission would launch (and hold) work nobody
            # will ever finalize
            if getattr(drain.fused_handle, "is_launch_ticket", False):
                drain.fused_handle.cancel()
            # undo this drain's projection refs before the caller
            # aborts the ops (a stale projection would quietly push
            # every later append of these objects off the fused path)
            for _, oid, _, _ in work:
                self._sim_refs[oid] -= 1
                if self._sim_refs[oid] <= 0:
                    del self._sim_refs[oid]
                    self._sim_chunk.pop(oid, None)
            raise
        # submit half done: the device work is in flight, no host sync
        # has happened (the launch/materialize split makes host-vs-
        # device wait attributable per op).  Only ops that contributed
        # encode extents get the event
        worked = {id(op) for op, _, _, _ in work}
        for op in ready:
            if id(op) in worked:
                op.top.mark_event("ec_encode_launch")
        drain.work = [(op, oid, e, run)
                      for (op, oid, e, _), run in zip(work, runs)]
        self.batched_launches += 1 + (1 if fused_idx and plain_idx
                                      else 0)
        self.batched_extents += len(work)
        drain.t_assemble = _time.perf_counter() - t0
        if self.perf:
            self.perf.inc("ec_drain_extents", len(work))
            self.perf.tinc("ec_drain_assemble", drain.t_assemble)
        return drain

    def _drain_pipeline(self) -> None:
        """Completion policy: keep up to dispatch_depth drains in
        flight while more work is imminent (a pipeline window is open,
        or ops are queued behind us); otherwise flush — a lone op with
        nothing behind it completes synchronously, preserving the
        pre-pipeline contract."""
        if self._completing:
            return
        self._completing = True
        try:
            while self._inflight:
                more = (self._pipeline_win > 0
                        or bool(self.waiting_state)
                        or bool(self.waiting_reads
                                and self.waiting_reads[0]
                                .pending_reads == 0))
                allowed = self.dispatch_depth if more else 0
                if len(self._inflight) <= allowed:
                    break
                self._complete_drain(self._inflight.popleft())
        finally:
            self._completing = False
        if self.perf:
            self.perf.set("ec_inflight_depth", len(self._inflight))

    # -- completion half: materialize + fold + sub-writes -------------------

    def _drop_sim_refs(self, drain: _Drain) -> None:
        """Drop this drain's projection refs; the LAST in-flight drain
        touching an object releases its _sim_chunk entry so the next
        submit re-seeds from the (now current) hinfo.  Must run on
        EVERY completion outcome — a leaked ref would strand a stale
        projection and silently push all later appends of the object
        off the fused path."""
        for _, oid, _, _ in drain.work:
            self._sim_refs[oid] -= 1
            if self._sim_refs[oid] <= 0:
                del self._sim_refs[oid]
                self._sim_chunk.pop(oid, None)

    def _complete_drain(self, drain: _Drain) -> None:
        import time as _time
        t0 = _time.perf_counter()
        prof = device_profiler()
        try:
            try:
                fh = drain.fused_handle
                if fh is None:
                    fused_res = []
                elif getattr(fh, "is_launch_ticket", False):
                    # launch-queue drain: result() forces the shared
                    # super-batch to launch if the window hasn't fired
                    # (flush-on-demand keeps lone-PG sync semantics)
                    # and demuxes THIS submission's per-run results
                    fused_res = fh.result()
                    self._note_fused_path(fh.path)
                else:
                    t_f = _time.perf_counter()
                    fused_res = \
                        self.ec_impl.encode_extents_with_crc_finalize(fh)
                    prof.materialized(drain.prof_fused,
                                      _time.perf_counter() - t_f)
                plain_par = None
                if drain.plain_handle is not None:
                    kind, h = drain.plain_handle
                    t_p = _time.perf_counter()
                    if kind == "queue":
                        plain_par = np.asarray(h.result())
                    elif kind == "mesh":
                        # _mesh_fallen: the plane was disabled after
                        # this drain launched — its own future may
                        # still materialize (and aborts cleanly if not)
                        mc = self.mesh_codec or \
                            getattr(self, "_mesh_fallen", None)
                        if mc is None:
                            raise RuntimeError(self.mesh_error or
                                               "mesh plane disabled")
                        plain_par = mc.encode_flat_finalize(h)
                        prof.materialized(drain.prof_plain,
                                          _time.perf_counter() - t_p)
                    elif kind == "plugin":
                        plain_par = self.ec_impl.encode_chunks_finalize(h)
                        prof.materialized(drain.prof_plain,
                                          _time.perf_counter() - t_p)
                    else:
                        plain_par = h
            except Exception as e:  # noqa: BLE001 — device/encode failure
                if self.perf:
                    self.perf.inc("ec_drain_errors")
                # the fused and plain halves are separate queue
                # tickets: when one raises, withdraw the other if it
                # is still pending — otherwise the window worker
                # launches it for nobody (post-launch cancel is a
                # no-op and the unread results are simply dropped)
                for h in (drain.fused_handle,
                          drain.plain_handle[1]
                          if drain.plain_handle is not None else None):
                    if getattr(h, "is_launch_ticket", False):
                        h.cancel()
                if drain.plain_handle is not None and \
                        drain.plain_handle[0] == "mesh":
                    # mesh finalize failure: abort THIS drain's ops,
                    # fall back to the single-chip plane for all later
                    # drains (reference analog: marking the backend's
                    # transport down rather than retrying into it)
                    self._disable_mesh(e)
                for op in drain.ops:
                    self._abort_op(op, e)
                return
            device_dt = _time.perf_counter() - t0
            worked = {id(op) for op, _, _, _ in drain.work}
            # trace stitching (ops/profiler.py): the launch ids that
            # served this drain land as events on every contributing
            # op's timeline — and a first-compile that stalled past
            # the threshold lands FIRST, so slow-op blame (largest
            # gap ends at the event) names the bucket that compiled
            # instead of a bare "ec_encode_materialize"
            stitches = []
            for src in (fh, drain.plain_handle[1]
                        if drain.plain_handle is not None else None):
                if getattr(src, "is_launch_ticket", False) and \
                        src.launch_id is not None:
                    stitches.append((src.launch_id, src.bucket,
                                     src.compiled, src.compile_s,
                                     src.cache_hit))
            for rec in (drain.prof_fused, drain.prof_plain):
                if rec is not None:
                    stitches.append((rec.launch_id, rec.bucket,
                                     rec.compiled, rec.compile_s,
                                     rec.cache_hit))
            stall_s = prof.stall_s
            for op in drain.ops:
                if id(op) in worked:
                    for lid, bucket, compiled, comp_s, c_hit in stitches:
                        # a persistent-cache hit is a fast first-launch,
                        # not a stall — it never takes the compile blame
                        if compiled and not c_hit and comp_s >= stall_s:
                            op.top.mark_event(
                                f"first_compile({bucket})")
                        op.top.mark_event(f"launch({lid})")
                    op.top.mark_event("ec_encode_materialize")
            encoded_by_op: dict[int, dict] = {id(op): {}
                                              for op in drain.ops}
            crcs_by_op: dict[int, dict] = {id(op): {} for op in drain.ops}
            fused_ls: dict[int, tuple] = {}
            for i, (op, oid, e, run) in enumerate(drain.work):
                if drain.kinds[i] == "fused":
                    par, l, tail, body = fused_res[drain.fused_pos[i]]
                    par = np.asarray(par)
                    fused_ls[i] = (l, tail, body)
                else:
                    col = drain.plain_cols[i]
                    par = plain_par[:, col:col + run.shape[1]]
                encoded_by_op[id(op)][(oid, e.off)] = \
                    np.concatenate([run, par], axis=0)
            self._fold_drain_crcs(drain, encoded_by_op, fused_ls,
                                  crcs_by_op)
            t1 = _time.perf_counter()
            for op in drain.ops:
                try:
                    self._commit_op(op, encoded_by_op[id(op)],
                                    crcs_by_op[id(op)])
                except Exception as e:  # noqa: BLE001
                    if self.perf:
                        self.perf.inc("ec_drain_errors")
                    self._abort_op(op, e)
            if self.perf:
                self.perf.tinc("ec_drain_device", device_dt)
                self.perf.tinc("ec_drain_commit",
                               _time.perf_counter() - t1)
        finally:
            self._drop_sim_refs(drain)

    def _fold_drain_crcs(self, drain: _Drain, encoded_by_op: dict,
                         fused_ls: dict, crcs_by_op: dict) -> None:
        """ONE ordered host pass over the drain computing cumulative
        shard crcs for every appending extent: fused extents fold the
        device-combined L (O(1) combines per shard), plain extents
        (mesh drains, CPU plugins) fold all k+m shard rows per run in
        a single vectorized crc32c_rows call.  Seeds chain per object
        through the walk exactly as generate_transactions will apply
        them; a mismatch (projection raced a truncate/delete) simply
        yields no precomputed crc and generate falls back to its own
        host append — correctness never depends on the projection."""
        from ..common import crc32c as _crc
        sim_size: dict[hobject_t, int] = {}
        sim_hash: dict[hobject_t, list[int]] = {}
        items_by_op: dict[int, list[int]] = {}
        for i, (op, _, _, _) in enumerate(drain.work):
            items_by_op.setdefault(id(op), []).append(i)
        for op in drain.ops:
            for oid, objop in op.txn.ops.items():
                if objop.delete:
                    # recreate seeds from the op's FRESH plan hinfo
                    sim_size[oid] = 0
                    sim_hash.pop(oid, None)
            for i in items_by_op.get(id(op), []):
                _, oid, e, run = drain.work[i]
                hinfo = op.plan.hash_infos[oid]
                chunk_off = (self.sinfo
                             .aligned_logical_offset_to_chunk_offset(
                                 e.off))
                cur = sim_size.get(oid, hinfo.total_chunk_size)
                width = run.shape[1]
                if chunk_off != cur:
                    sim_size[oid] = max(cur, chunk_off + width)
                    sim_hash.pop(oid, None)
                    continue
                seeds = sim_hash.get(
                    oid, list(hinfo.cumulative_shard_hashes))
                if i in fused_ls:
                    l, tail, body = fused_ls[i]
                    crcs = self.ec_impl.fold_extent_crcs(
                        l, tail, seeds, body)
                else:
                    crcs = _crc.crc32c_rows(
                        encoded_by_op[id(op)][(oid, e.off)], seeds)
                sim_hash[oid] = crcs
                sim_size[oid] = cur + width
                crcs_by_op[id(op)][(oid, e.off)] = crcs
            for oid, objop in op.txn.ops.items():
                if objop.truncate_to is not None:
                    sim_size[oid] = \
                        self.sinfo.logical_to_next_chunk_offset(
                            objop.truncate_to)
                    sim_hash.pop(oid, None)

    def _abort_op(self, op: ECOp, err: Exception) -> None:
        """Failure path (satellite of the pipeline work): an op that
        dies before/at commit is routed through the in-order finish
        queue with its error attached — _try_finish_rmw releases its
        pinned extents (stale assembled bytes must never satisfy a
        later drain's overlay), drops its projection refs, and acks it
        AFTER every earlier op, so the pipeline never wedges and acks
        never reorder."""
        op.error = err
        op.state = "failed"
        op.pending_commits = 0
        if op not in self.waiting_commit:
            self.waiting_commit.append(op)
        self._try_finish_rmw()

    def _commit_op(self, op: ECOp, encoded: dict,
                   crcs: dict | None = None) -> None:
        # PG log entries with rollback info (reference log_operation :958
        # + ecbackend.rst local-rollbackability).  Snapshot rollback
        # state BEFORE generate_transactions mutates the hinfo.
        entries: list[LogEntry] = []
        gen_oids: set[hobject_t] = set()
        for oid, objop in op.txn.ops.items():
            rb = RollbackInfo()
            old_size = op.plan.sizes.get(oid, 0)
            hinfo = op.plan.hash_infos.get(oid)
            existed = old_size > 0 or (
                hinfo is not None and hinfo.total_chunk_size > 0)
            if not objop.delete:
                rb.append_old_size = old_size
                aligned_old = self.sinfo.logical_to_next_stripe_offset(
                    old_size)
                rb.old_chunk_size = (
                    self.sinfo.aligned_logical_offset_to_chunk_offset(
                        aligned_old))
                # pure_append == undo is a truncate: tail-only writes,
                # no truncate of existing data, and no user xattr
                # mutations
                rb.pure_append = (
                    bool(op.plan.will_write.get(oid))
                    and all(e.off >= aligned_old
                            for e in op.plan.will_write.get(oid, []))
                    and (objop.truncate_to is None or not existed)
                    and not objop.attrs)
                rb.hinfo_old = hinfo.encode() if existed else None
            # anything not a pure append keeps the old object under a
            # generation so the shard can roll it back locally
            # (reference ecbackend.rst local-rollbackability contract)
            if objop.delete or (existed and not rb.pure_append):
                rb.kept_generation = op.version.version
                gen_oids.add(oid)
            self.log.add(LogEntry(
                op.version, oid,
                LogOp.DELETE if objop.delete else LogOp.MODIFY, rb))
            entries.append(self.log.entries[-1])
        txns, _ = ect.generate_transactions(
            self.sinfo, self.n, op.plan, op.txn, encoded, crcs,
            gen=op.version.version, gen_oids=gen_oids)
        op.state = "committing"
        op.pending_commits = self.n
        self.waiting_commit.append(op)
        top = op.top
        tracked = top.is_tracked
        # one child span for the whole shard fan-out (the holder's
        # sub-op description carries the shard); per-shard spans would
        # cost n uuid draws per op on the hot path
        wire_trace = top.trace.child().to_wire() if tracked else None
        if tracked:
            top.mark_event("sub_write_sent")

        def on_commit(shard: int) -> None:
            if tracked:
                top.mark_event(f"sub_write_ack({shard})")
            with self.lock:
                op.pending_commits -= 1
                if op.pending_commits == 0:
                    self._try_finish_rmw()

        rf = self.log.rollforward_to
        for s in range(self.n):
            try:
                self.shards.sub_write(s, txns[s], on_commit,
                                      log_entries=entries,
                                      at_version=op.version,
                                      rollforward_to=rf,
                                      trace=wire_trace,
                                      top=top if tracked else None)
            except Exception as e:  # noqa: BLE001 — a failed sub-write
                # must not wedge the in-order commit queue: count the
                # shard as resolved (failed) so the op drains, carrying
                # the error to the ack (reference marks the PG
                # inconsistent and lets scrub/peering repair the shard)
                op.error = op.error or e
                if self.perf:
                    self.perf.inc("ec_drain_errors")
                on_commit(s)

    def _try_finish_rmw(self) -> None:
        """reference try_finish_rmw :2103: in-order completion, advance
        rollforward bounds, ack clients."""
        while self.waiting_commit and \
                self.waiting_commit[0].pending_commits == 0:
            op = self.waiting_commit.pop(0)
            op.state = "failed" if op.error is not None else "done"
            op.top.mark_event("failed" if op.error is not None
                              else "commit")
            self.log.roll_forward_to(op.version)
            # unpin EXACTLY what this op presented + drop projected
            # refs (op.pinned, not the plan: a mid-assembly abort may
            # have pinned only a prefix of the plan's extents)
            for oid, off, length in op.pinned:
                self.extent_cache.release(oid, off, length)
            op.pinned.clear()
            for oid in op.txn.ops:
                proj = self._projected.get(oid)
                if proj is not None:
                    proj["refs"] -= 1
                    if proj["refs"] <= 0:
                        del self._projected[oid]
            self.completed += 1
            op.on_commit()
        self.check_ops()

    # -- client reads (reference objects_read_and_reconstruct :2345) --------

    def read(self, oid: hobject_t, off: int = 0,
             length: int | None = None) -> np.ndarray:
        """Client read.  Healthy path: the k data shards answer and the
        logical bytes reassemble without a decode.  Degraded path
        (reconstruct-on-read, docs/REPAIR.md): any data-shard failure
        fans out to the parity shards IMMEDIATELY — known-down holders
        fail synchronously, so a degraded object pays one extra fan-out,
        not a timeout — and the missing rows rebuild through the
        batched decode path (launch queue / mesh / plugin decode), the
        same machinery background repair uses.  The fan-out wait is
        `osd_ec_read_timeout` (was a hardcoded 30 s) and every expiry
        counts in ec_read_timeouts instead of silently returning
        short."""
        size = self._get_size(oid)
        if length is None:
            length = size - off
        if length <= 0 or off >= size:
            return np.empty(0, dtype=np.uint8)
        start, span = self.sinfo.offset_len_to_stripe_bounds(off, length)
        chunk_off = self.sinfo.aligned_logical_offset_to_chunk_offset(start)
        chunk_len = span // self.k
        glock = threading.Lock()
        got: dict[int, np.ndarray] = {}
        failed: set[int] = set()
        ready = threading.Event()
        issued = [0]

        def on_done(shard, data):
            with glock:       # replies race on reader threads
                if data is None:
                    failed.add(shard)
                else:
                    got[shard] = data
                # set INSIDE the lock: the degraded transition below
                # clears + re-arms (issued k -> n) under the same
                # lock, so a reply's stale-issued fire decision can
                # never land after the clear
                if len(got) >= self.k or \
                        len(got) + len(failed) >= issued[0]:
                    ready.set()
        on_done.loop_safe = True      # store + Event.set only: may run
        #                               inline on the reactor

        issued[0] = self.k
        self.shards.sub_read_batch(
            [(s, oid, chunk_off, chunk_len) for s in range(self.k)],
            on_done)
        timeout = self.read_timeout
        with glock:
            need_parity = bool(failed) and len(got) < self.k
        if not need_parity:
            if not ready.wait(timeout=timeout):
                if self.perf:
                    self.perf.inc("ec_read_timeouts")
            with glock:
                need_parity = len(got) < self.k
        if need_parity:
            # degraded: fan out to parity shards until k gathered
            # (reference get_remaining_shards :1633 / fast_read)
            with glock:
                ready.clear()
                issued[0] = self.n
                if len(got) >= self.k or \
                        len(got) + len(failed) >= self.n:
                    ready.set()
            self.shards.sub_read_batch(
                [(s, oid, chunk_off, chunk_len)
                 for s in range(self.k, self.n)], on_done)
            if not ready.wait(timeout=timeout) and self.perf:
                self.perf.inc("ec_read_timeouts")
        with glock:
            have = dict(got)
        if len(have) < self.k:
            raise ErasureCodeError(5, f"unrecoverable read {oid}")
        if set(range(self.k)) <= set(have):
            use = {s: have[s] for s in range(self.k)}
            logical = ec_util.decode(self.sinfo, self.ec_impl, use, span)
        else:
            logical = self._reconstruct_read(oid, have, chunk_len, span)
        return logical[off - start:off - start + length]

    def _reconstruct_read(self, oid: hobject_t,
                          have: dict[int, np.ndarray],
                          chunk_len: int, span: int) -> np.ndarray:
        """Reconstruct-on-read: rebuild the missing data shards of a
        degraded read through the batched decode path — the per-host
        launch queue (co-batched with other PGs' repair decodes) when
        one is wired, the mesh collective when that plane is up, the
        plugin decode otherwise.  Sub-chunked codes (CLAY) keep the
        dict-decode path: a partial chunk run does not respect their
        plane layout."""
        if self.perf:
            self.perf.inc("ec_reconstruct_reads")
            self.perf.inc("ec_reconstruct_read_bytes", span)
        use = dict(list(sorted(have.items()))[: self.k])
        if self.ec_impl.get_sub_chunk_count() != 1:
            return ec_util.decode(self.sinfo, self.ec_impl, use, span)
        survivors = tuple(sorted(use))
        erasures = [s for s in range(self.n) if s not in use]
        targets = tuple(s for s in range(self.k) if s not in use)
        dec = None
        if self.mesh_codec is not None:
            try:
                avail = np.stack([use[s] for s in survivors])
                rows = self.mesh_codec.decode_flat(avail, survivors,
                                                   targets)
                dec = np.zeros((self.n, chunk_len), dtype=np.uint8)
                for s, d in use.items():
                    dec[s] = d
                for i, t in enumerate(targets):
                    dec[t] = rows[i]
            except Exception as e:  # noqa: BLE001 — mesh died mid-read
                self._disable_mesh(e)
                dec = None
        if dec is None:
            dense = np.zeros((self.n, chunk_len), dtype=np.uint8)
            for s, d in use.items():
                dense[s] = d
            if self._launch_queue is not None:
                ticket = self._launch_queue.submit_decode(
                    self.ec_impl, dense, erasures, owner=id(self))
                dec = np.asarray(ticket.result())
            else:
                dec = np.asarray(
                    self.ec_impl.decode_chunks(dense, erasures))
        nstripes = chunk_len // self.sinfo.chunk_size
        logical = dec[: self.k] \
            .reshape(self.k, nstripes, self.sinfo.chunk_size) \
            .transpose(1, 0, 2).reshape(-1)
        return logical[:span]

    # -- recovery (reference continue_recovery_op :570) ---------------------
    #
    # Batched and mesh-native (docs/MULTICHIP.md): an OSD-loss storm
    # queues MANY objects missing the SAME shards, so the batch entry
    # fans out every object's survivor reads concurrently, groups the
    # results by (survivors, targets) recovery geometry, and rebuilds
    # each group in ONE decode — a sharded collective launch on the
    # mesh plane (survivor rows over the 'shard' axis), or a single
    # concatenated host decode on the single-chip plane.  The
    # reference's continue_recovery_op gathers k shards to one node
    # and decodes per object; here the whole queue is a handful of
    # launches.

    def recover_shard(self, oid: hobject_t, missing: list[int],
                      push: Callable[[int, np.ndarray, HashInfo], None]
                      ) -> None:
        """Rebuild `missing` shards of oid from any k survivors and hand
        each to `push(shard, data, hinfo)` (the caller writes it to the
        new home — locally or over the wire)."""
        res = self.recover_shards_batch([(oid, list(missing))],
                                        lambda _oid: push)
        err = res.get(oid)
        if err is not None:
            raise err

    def _start_recovery_reads(self, oid: hobject_t,
                              missing: list[int]) -> dict:
        """Phase 1 of a batched recovery: metadata probe + survivor
        read fan-out for ONE object, returning the gathering state
        WITHOUT waiting — a storm of objects issues all its reads
        before the first wait, so shard holders serve them
        concurrently."""
        hinfo = self._get_hinfo(oid)
        chunk_len = None
        for s in range(self.n):
            if s in missing:
                continue
            chunk_len = self.shards.stat(s, oid)
            if chunk_len is not None:
                break
        if chunk_len is None:
            raise ErasureCodeError(5, f"cannot recover {oid}: no survivor")
        got: dict[int, np.ndarray] = {}
        glock = threading.Lock()
        done = {"n": 0}
        ready = threading.Event()
        sources = [s for s in range(self.n) if s not in missing]

        def on_done(sh, d):
            with glock:       # replies race on reader threads
                if d is not None:
                    got[sh] = d
                done["n"] += 1
                fire = len(got) >= self.k or done["n"] >= len(sources)
            if fire:
                ready.set()
        on_done.loop_safe = True      # store + Event.set only

        self.shards.sub_read_batch(
            [(s, oid, 0, chunk_len) for s in sources], on_done)
        return {"oid": oid, "missing": list(missing), "hinfo": hinfo,
                "chunk_len": chunk_len, "got": got, "glock": glock,
                "ready": ready}

    def _verify_recovered(self, st: dict, s: int,
                          data: np.ndarray) -> None:
        """Verify a rebuilt shard against the stored hinfo (reference
        handle_sub_read crc check, ECBackend.cc:991)."""
        from ..common import crc32c as _crc
        hinfo = st["hinfo"]
        want = hinfo.get_chunk_hash(s)
        got_crc = _crc.crc32c(data.tobytes(), 0xFFFFFFFF)
        if hinfo.crc_valid and \
                hinfo.total_chunk_size == st["chunk_len"] and \
                got_crc != want:
            raise ErasureCodeError(
                5, f"recovered shard {s} of {st['oid']} crc mismatch "
                   f"{got_crc:#x} != {want:#x}")

    # objects per recovery sub-batch: bounds BOTH the concurrent
    # survivor-read fan-out and the peak survivor-chunk memory
    # (~max * k * chunk_len held at once) — a storm on a huge PG must
    # not OOM the daemon or flood peers the way an uncapped all-at-
    # once fan-out would, while still collapsing to one launch per
    # geometry group within each slice
    RECOVER_BATCH_MAX = 64
    # max concatenated byte width of one grouped recovery decode
    # launch (single source: parallel/launch_queue, which enforces the
    # same cap on cross-PG coalescing): with the queue's pow2 padding
    # this bounds the decode jit-bucket universe to {pow2 <= cap} x
    # {cardinality <= m} — small enough for the boot prewarm
    # (ops/prewarm.py) to cover exactly, so a recovery storm never
    # mints a first-seen bucket.  A single object's chunk wider than
    # the cap still launches alone (an object's chunk is atomic).
    DECODE_MAX_LAUNCH_W = DECODE_MAX_LAUNCH_W

    def recover_shards_batch(
            self, items: list[tuple[hobject_t, list[int]]],
            push_for: Callable[[hobject_t], Callable]) -> dict:
        """Rebuild many objects' missing shards in as few decode
        launches as the recovery geometry allows.  items: [(oid,
        missing_shards)]; push_for(oid) -> the per-object
        push(shard, data, hinfo) sink.  Returns {oid: None on success
        | the per-object Exception} — one object's failure never
        blocks the rest of the queue.  Processed in bounded slices
        (RECOVER_BATCH_MAX) so arbitrarily long recovery queues run
        at bounded memory and read concurrency."""
        results: dict[hobject_t, Exception | None] = {}
        step = self.RECOVER_BATCH_MAX
        for lo in range(0, len(items), step):
            results.update(self._recover_shards_slice(
                items[lo:lo + step], push_for))
        return results

    def _recover_shards_slice(
            self, items: list[tuple[hobject_t, list[int]]],
            push_for: Callable[[hobject_t], Callable]) -> dict:
        results: dict[hobject_t, Exception | None] = {}
        states: list[dict] = []
        clay_states: list[dict] = []
        # phase 1: every object's survivor reads in flight before any
        # wait (the fan-out IS the storm's concurrency).  Single-shard
        # losses of a sub-chunked plugin with a repair lowering take
        # the bandwidth-optimal CLAY path: only the q^{t-1} repair
        # planes of d helpers are read (1/q of each helper chunk)
        for oid, missing in items:
            try:
                st = None
                if self._clay_repair_eligible(missing):
                    st = self._start_clay_repair_reads(oid, missing[0])
                if st is not None:
                    clay_states.append(st)
                else:
                    states.append(self._start_recovery_reads(
                        oid, missing))
            except Exception as e:  # noqa: BLE001
                results[oid] = e
        # phase 2 (CLAY): collect plane reads; any helper failure falls
        # back to the full-read decode path for that object
        clay_groups: dict[tuple, list[dict]] = {}
        for st in clay_states:
            st["ready"].wait(timeout=self.read_timeout)
            with st["glock"]:
                complete = not st["failed"] and st["left"] == 0
            if not complete:
                if self.perf:
                    self.perf.inc("ec_clay_repair_fallbacks")
                try:
                    states.append(self._start_recovery_reads(
                        st["oid"], st["missing"]))
                except Exception as e:  # noqa: BLE001
                    results[st["oid"]] = e
                continue
            if self.perf:
                self.perf.inc("ec_repair_helper_bytes",
                              st["helper_bytes"])
            clay_groups.setdefault(
                (st["lost"], st["helpers"], st["chunk_len"]),
                []).append(st)
        for (lost, helpers, _clen), sts in clay_groups.items():
            try:
                self._clay_repair_group(lost, helpers, sts, push_for)
            except Exception as e:  # noqa: BLE001 — whole-group launch
                for st in sts:
                    results.setdefault(st["oid"], e)
                continue
            for st in sts:
                results.setdefault(st["oid"], st.get("error"))
        # phase 2 (full): collect; drop objects that can't reach k
        # survivors
        groups: dict[tuple, list[dict]] = {}
        for st in states:
            st["ready"].wait(timeout=self.read_timeout)
            with st["glock"]:
                # snapshot under a DIFFERENT name: `got` is the
                # closure cell late on_done callbacks still write into
                have = dict(st["got"])
            if len(have) < self.k:
                results[st["oid"]] = ErasureCodeError(
                    5, f"cannot recover {st['oid']}: "
                       f"{len(have)} < k={self.k}")
                continue
            st["have"] = have
            if self.perf:
                self.perf.inc("ec_repair_helper_bytes",
                              len(have) * st["chunk_len"])
            survivors = tuple(sorted(have))[: self.k]
            targets = tuple(sorted(st["missing"]))
            erasures = tuple(s for s in range(self.n) if s not in have)
            st["survivors"] = survivors
            groups.setdefault((survivors, targets, erasures),
                              []).append(st)
        # phase 3: one decode per geometry group
        for (survivors, targets, erasures), sts in groups.items():
            try:
                self._decode_recovery_group(survivors, targets,
                                            erasures, sts, push_for)
            except Exception as e:  # noqa: BLE001 — whole-group launch
                for st in sts:
                    results.setdefault(st["oid"], e)
                continue
            for st in sts:
                results.setdefault(st["oid"],
                                   st.get("error"))
        return results

    # -- CLAY plane-read repair (docs/REPAIR.md) ----------------------------

    def _clay_repair_eligible(self, missing: list[int]) -> bool:
        return (self._clay_repair and len(missing) == 1 and
                self.ec_impl.get_sub_chunk_count() > 1 and
                hasattr(self.ec_impl, "repair_matrix"))

    def _clay_plan(self, lost: int, helpers: tuple[int, ...]):
        """Cached ClayRepairPlan for one (lost, helper set) — the host
        plane-solver runs once, every repair after is a batched GF
        matmul (parallel/mesh.ClayRepairPlan)."""
        key = (lost, helpers)
        plan = self._clay_plans.get(key)
        if plan is None:
            from ..parallel.mesh import ClayRepairPlan
            plan = ClayRepairPlan.build(self.ec_impl, lost, helpers)
            self._clay_plans[key] = plan
        return plan

    def _start_clay_repair_reads(self, oid: hobject_t,
                                 lost: int) -> dict | None:
        """Phase 1 of a CLAY repair: fan out the repair-plane sub-chunk
        runs of the d chosen helpers — 1/q of each helper chunk, the
        bandwidth-optimal read set — without waiting.  Returns None
        when the geometry can't serve the plane path (no helper set,
        chunk not sub-aligned): the caller falls back to full reads."""
        impl = self.ec_impl
        sub = impl.get_sub_chunk_count()
        hinfo = self._get_hinfo(oid)
        chunk_len = None
        for s in range(self.n):
            if s == lost:
                continue
            chunk_len = self.shards.stat(s, oid)
            if chunk_len is not None:
                break
        if chunk_len is None:
            raise ErasureCodeError(5,
                                   f"cannot recover {oid}: no survivor")
        if chunk_len % sub:
            return None
        helpers = impl.choose_helpers(
            lost, set(range(self.n)) - {lost})
        if helpers is None:
            return None
        helpers = tuple(sorted(helpers))
        sub_size = chunk_len // sub
        planes = impl.repair_planes(lost)
        runs = impl._runs(planes)
        row0 = []
        acc = 0
        for _s0, cnt in runs:
            row0.append(acc)
            acc += cnt
        got = {h: np.zeros((len(planes), sub_size), dtype=np.uint8)
               for h in helpers}
        glock = threading.Lock()
        state = {"oid": oid, "missing": [lost], "lost": lost,
                 "helpers": helpers, "hinfo": hinfo,
                 "chunk_len": chunk_len, "sub_size": sub_size,
                 "got": got, "glock": glock, "failed": set(),
                 "left": len(helpers) * len(runs),
                 "helper_bytes": len(helpers) * len(planes) * sub_size,
                 "ready": threading.Event()}

        # one callback closure per run index: on_done only reports the
        # shard, so the run identity must ride the closure
        for ri, (s0, cnt) in enumerate(runs):
            def make_cb(r0=row0[ri], cnt=cnt):
                def cb(sh, d):
                    with glock:
                        if d is None:
                            state["failed"].add(sh)
                        else:
                            if d.size < cnt * sub_size:
                                # sparse tail: pad like the healthy
                                # shard-read path does
                                d = np.concatenate(
                                    [d, np.zeros(cnt * sub_size - d.size,
                                                 dtype=np.uint8)])
                            got[sh][r0:r0 + cnt] = \
                                d.reshape(cnt, sub_size)
                        state["left"] -= 1
                        fire = state["left"] == 0 or state["failed"]
                    if fire:
                        state["ready"].set()
                cb.loop_safe = True      # store + Event.set only
                return cb
            self.shards.sub_read_batch(
                [(h, oid, s0 * sub_size, cnt * sub_size)
                 for h in helpers], make_cb())
        return state

    def _clay_repair_group(self, lost: int, helpers: tuple[int, ...],
                           sts: list[dict], push_for) -> None:
        """Rebuild one (lost, helpers) CLAY group: every object's
        stacked helper plane rows ride ONE batched GF matmul — the
        mesh collective when that plane is up, the per-host launch
        queue (co-batched with writes and other PGs' repairs)
        otherwise, the plan's own device/host apply as the floor."""
        plan = self._clay_plan(lost, helpers)
        rows_list = [
            self.ec_impl.repair_rows(
                lost, {h: st["got"][h] for h in helpers}, helpers)
            for st in sts]
        rebuilt_list = None
        if self.mesh_codec is not None:
            try:
                rebuilt_list = self.mesh_codec.clay_repair_batch(
                    plan, rows_list)
                if self.perf:
                    self.perf.inc("ec_mesh_repair_launches")
            except Exception as e:  # noqa: BLE001 — mesh died mid-storm
                self._disable_mesh(e)
                rebuilt_list = None
        if rebuilt_list is None:
            if self._launch_queue is not None:
                from ..common.util import concat_columns, split_columns
                big, widths = concat_columns(rows_list)
                out = np.asarray(self._launch_queue.submit_clay_repair(
                    plan, big, owner=id(self)).result())
                rebuilt_list = split_columns(out, widths)
            else:
                rebuilt_list = plan.apply_batch(rows_list)
        if self.perf:
            self.perf.inc("ec_clay_repair_launches")
            self.perf.inc("ec_clay_repairs", len(sts))
        for st, rebuilt in zip(sts, rebuilt_list):
            try:
                data = np.ascontiguousarray(
                    np.asarray(rebuilt), dtype=np.uint8).reshape(-1)
                self._verify_recovered(st, lost, data)
                push_for(st["oid"])(lost, data, st["hinfo"])
                if self.perf:
                    self.perf.inc("ec_repair_reconstructed_bytes",
                                  st["chunk_len"])
            except Exception as e:  # noqa: BLE001 — per-object verify
                st["error"] = e

    def _decode_recovery_group(self, survivors, targets, erasures,
                               sts: list[dict], push_for) -> None:
        """Rebuild one (survivors, targets) geometry group: a single
        mesh collective launch (byte axes of all objects concatenated,
        survivor rows sharded over 'shard') when the mesh plane is up,
        else one concatenated host decode; sub-chunked codes (CLAY)
        decode per object — their plane layout does not concatenate
        along the byte axis."""
        rebuilt_per_st: list[dict[int, np.ndarray]] = []
        meshed = False
        # sub-chunked codes (CLAY) are not an RS matrix apply AND do
        # not concatenate along the byte axis — never mesh them (the
        # service path refuses matrix-less plugins, but an injected
        # codec must hit the same guard)
        if self.mesh_codec is not None and \
                self.ec_impl.get_sub_chunk_count() == 1:
            try:
                avail_list = [
                    np.stack([st["have"][s] for s in survivors])
                    for st in sts]
                rows_list = self.mesh_codec.decode_flat_batch(
                    avail_list, survivors, targets)
                meshed = True
                if self.perf:
                    self.perf.inc("ec_mesh_repair_launches")
                for rows in rows_list:
                    rebuilt_per_st.append(
                        {s: rows[i] for i, s in enumerate(targets)})
            except Exception as e:  # noqa: BLE001 — mesh died mid-storm
                # containment: fall back to the host decode for this
                # (and every later) group; recovery itself proceeds
                self._disable_mesh(e)
                meshed = False
        if not meshed:
            if self.ec_impl.get_sub_chunk_count() == 1:
                # one concatenated decode for the whole group — through
                # the per-host launch queue when one is wired, so
                # recovery decodes coalesce with OTHER PGs' repairs
                # (and share occupancy accounting with writes) instead
                # of issuing a private launch
                # width-capped slices (DECODE_MAX_LAUNCH_W): the
                # concatenated width, pow2-padded by the queue, stays
                # inside the prewarm-enumerable bucket set instead of
                # growing with the storm's queue depth
                slices: list[list[dict]] = []
                cur: list[dict] = []
                cur_w = 0
                for st in sts:
                    w = st["chunk_len"]
                    if cur and cur_w + w > self.DECODE_MAX_LAUNCH_W:
                        slices.append(cur)
                        cur, cur_w = [], 0
                    cur.append(st)
                    cur_w += w
                if cur:
                    slices.append(cur)
                for chunk_sts in slices:
                    widths = [st["chunk_len"] for st in chunk_sts]
                    big = np.zeros((self.n, sum(widths)),
                                   dtype=np.uint8)
                    col = 0
                    for st, w in zip(chunk_sts, widths):
                        for s, d in st["have"].items():
                            big[s, col:col + w] = d
                        col += w
                    if self._launch_queue is not None:
                        dec = np.asarray(
                            self._launch_queue.submit_decode(
                                self.ec_impl, big, list(erasures),
                                owner=id(self)).result())
                    else:
                        dec = self.ec_impl.decode_chunks(
                            big, list(erasures))
                    col = 0
                    for st, w in zip(chunk_sts, widths):
                        rebuilt_per_st.append(
                            {s: dec[s, col:col + w] for s in targets})
                        col += w
            else:
                for st in sts:
                    dense = np.zeros((self.n, st["chunk_len"]),
                                     dtype=np.uint8)
                    for s, d in st["have"].items():
                        dense[s] = d
                    dec = self.ec_impl.decode_chunks(dense,
                                                     list(erasures))
                    rebuilt_per_st.append({s: dec[s] for s in targets})
        for st, rebuilt in zip(sts, rebuilt_per_st):
            try:
                push = push_for(st["oid"])
                for s in st["missing"]:
                    data = rebuilt[s]
                    self._verify_recovered(st, s, data)
                    push(s, data, st["hinfo"])
                    if self.perf:
                        self.perf.inc("ec_repair_reconstructed_bytes",
                                      int(np.asarray(data).size))
            except Exception as e:  # noqa: BLE001 — per-object verify
                st["error"] = e
