"""PG log: per-PG op journal for recovery, EC rollback, and peering.

Re-expresses reference src/osd/PGLog.{h,cc} at the fidelity the EC
pipeline needs: an ordered list of entries keyed by eversion, each
carrying enough rollback state to locally undo it (the reference's
design constraint that EC ops be locally rollbackable —
doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27: append records
the old size, delete keeps the old generation, setattr keeps prior
values), plus the can_rollback_to / rollforward bounds ECBackend
advances in try_finish_rmw (reference ECBackend.cc:2115-2134).

The log is REPLICATED: every ECSubWrite carries its entries (reference
ECSubWrite.log_entries, src/osd/ECMsgTypes.h:38) and each shard persists
them durably alongside the data — omap of a per-PG meta object, the
analog of the reference's pglog omap keys in the pg meta collection
(src/osd/PGLog.cc _write_log_and_missing) — so a new primary can collect
shard logs and select the authoritative one after failover (reference
PeeringState::calc_acting / GetLog).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum

from .types import eversion_t, ghobject_t, hobject_t

# Reserved per-PG metadata object carrying the shard's log (omap) and
# info (xattr).  Filtered out of object enumeration (MPGList, scrub).
PG_META_NAME = "__pg_meta__"
INFO_ATTR = "_info"


def meta_oid(pool: int, shard: int) -> ghobject_t:
    return ghobject_t(hobject_t(pool, PG_META_NAME), shard=shard)


class LogOp(Enum):
    MODIFY = "modify"
    DELETE = "delete"
    ERROR = "error"


@dataclass
class RollbackInfo:
    """What a shard must remember to undo this entry locally."""
    append_old_size: int | None = None          # logical size before
    old_attrs: dict[str, bytes | None] | None = None  # prior xattr values
    kept_generation: int | None = None          # delete renamed to this gen
    hinfo_old: bytes | None = None              # prior hinfo xattr
    old_chunk_size: int | None = None           # per-shard size before
    pure_append: bool = False                   # undo == truncate


@dataclass
class LogEntry:
    version: eversion_t
    oid: hobject_t
    op: LogOp = LogOp.MODIFY
    rollback: RollbackInfo = field(default_factory=RollbackInfo)


@dataclass
class pg_info_t:
    """Shard-resident PG summary (reference osd_types.h pg_info_t, the
    slice peering needs: last_update orders logs inside an interval,
    last_epoch_started fences out shards that missed an interval)."""
    last_update: eversion_t = field(default_factory=eversion_t)
    last_epoch_started: int = 0

    def to_json(self) -> dict:
        return {"lu": [self.last_update.epoch, self.last_update.version],
                "les": self.last_epoch_started}

    @classmethod
    def from_json(cls, j: dict) -> "pg_info_t":
        return cls(eversion_t(*j["lu"]), j["les"])


def entry_to_wire(e: LogEntry) -> list:
    rb = e.rollback
    return [e.version.epoch, e.version.version,
            [e.oid.pool, e.oid.name, e.oid.key, e.oid.snap, e.oid.hash],
            e.op.value, rb.append_old_size, rb.old_chunk_size,
            rb.pure_append,
            rb.hinfo_old.hex() if rb.hinfo_old is not None else None,
            rb.kept_generation]


def entry_from_wire(w: list) -> LogEntry:
    return LogEntry(
        eversion_t(w[0], w[1]), hobject_t(*w[2]), LogOp(w[3]),
        RollbackInfo(append_old_size=w[4], old_chunk_size=w[5],
                     pure_append=w[6],
                     hinfo_old=bytes.fromhex(w[7]) if w[7] else None,
                     kept_generation=w[8] if len(w) > 8 else None))


def _omap_key(e: LogEntry) -> bytes:
    return (f"{e.version.epoch:010d}.{e.version.version:010d}."
            f"{e.oid.name}").encode()


class PGLog:
    def __init__(self) -> None:
        self.entries: list[LogEntry] = []
        self.head = eversion_t()            # newest logged
        self.tail = eversion_t()            # oldest kept
        self.can_rollback_to = eversion_t() # entries after this are undoable
        self.rollforward_to = eversion_t()  # entries before this are durable

    def add(self, entry: LogEntry) -> None:
        # >= not >: one txn's objects share the op version (reference
        # keeps one entry per object too, pg_log_entry_t per hobject)
        assert entry.version >= self.head, (entry.version, self.head)
        self.entries.append(entry)
        self.head = entry.version

    def roll_forward_to(self, v: eversion_t) -> list[LogEntry]:
        """Mark entries <= v irrevocable; returns the newly-stable ones
        (whose rollback state may be discarded / old gens trimmed)."""
        newly = [e for e in self.entries
                 if self.rollforward_to < e.version <= v]
        if v > self.rollforward_to:
            self.rollforward_to = v
        if v > self.can_rollback_to:
            self.can_rollback_to = v
        return newly

    def rollback_to(self, v: eversion_t) -> list[LogEntry]:
        """Drop entries newer than v; returns them newest-first so the
        caller can undo their store effects.  Only legal if v >=
        rollforward_to (can't undo what was rolled forward)."""
        assert v >= self.rollforward_to, (v, self.rollforward_to)
        undone = sorted((e for e in self.entries if e.version > v),
                        key=lambda e: e.version, reverse=True)
        self.entries = [e for e in self.entries if e.version <= v]
        self.head = v
        return undone

    def trim(self, to: eversion_t) -> None:
        """Discard entries <= to (reference log trimming)."""
        self.entries = [e for e in self.entries if e.version > to]
        if to > self.tail:
            self.tail = to


class ShardPGLog:
    """The shard-resident replicated log: entries + pg_info persisted in
    the store (omap + xattr of the per-PG meta object) in the SAME
    transaction as the data they describe, so the write and its log
    entry are atomic (reference ECBackend::handle_sub_write appends
    log_entries into the sub-write's ObjectStore::Transaction).

    Also owns shard-local rollback: a divergent shard undoes entries
    past the authoritative head using only its own persisted rollback
    state (the reference's "EC ops must be locally rollbackable"
    contract, ecbackend.rst:9-27).
    """

    def __init__(self, store, cid, shard: int):
        self.store = store
        self.cid = cid
        self.shard = shard
        self.moid = meta_oid(cid.pgid.pool, shard)
        self.log = PGLog()
        self.info = pg_info_t()
        self._load()

    def _load(self) -> None:
        try:
            raw = self.store.getattr(self.cid, self.moid, INFO_ATTR)
            self.info = pg_info_t.from_json(json.loads(raw.decode()))
        except KeyError:
            return
        try:
            omap = self.store.omap_get(self.cid, self.moid)
        except KeyError:
            omap = {}
        for key in sorted(omap):
            e = entry_from_wire(json.loads(omap[key].decode()))
            if e.version >= self.log.head:
                self.log.add(e)
        if self.log.entries:
            self.log.tail = self.log.entries[0].version

    def append_to_txn(self, txn, entries: list[LogEntry],
                      at_version: eversion_t) -> None:
        """Augment the shard data transaction with log persistence."""
        txn.touch(self.moid)
        if entries:
            txn.omap_setkeys(self.moid, {
                _omap_key(e): json.dumps(entry_to_wire(e)).encode()
                for e in entries})
        self.info.last_update = max(self.info.last_update, at_version)
        txn.setattr(self.moid, INFO_ATTR,
                    json.dumps(self.info.to_json()).encode())

    def record(self, entries: list[LogEntry], at_version: eversion_t
               ) -> None:
        """In-memory bookkeeping after the txn committed."""
        for e in entries:
            if e.version >= self.log.head:
                self.log.add(e)

    def advance_rollforward(self, rf: eversion_t) -> None:
        """Entries at or below rf are durable everywhere: their kept
        generations will never be rolled back to — reclaim them
        (reference trim_rollback_object on rollforward,
        ECBackend.cc try_finish_rmw)."""
        newly = self.log.roll_forward_to(rf)
        purge = [e for e in newly
                 if e.rollback.kept_generation is not None]
        if not purge:
            return
        txn = _txn()
        for e in purge:
            txn.remove(ghobject_t(e.oid, e.rollback.kept_generation,
                                  self.shard))
        self.store.queue_transactions(self.cid, [txn])

    def set_les(self, les: int) -> None:
        self.info.last_epoch_started = max(
            self.info.last_epoch_started, les)
        txn = _txn()
        txn.touch(self.moid)
        txn.setattr(self.moid, INFO_ATTR,
                    json.dumps(self.info.to_json()).encode())
        self.store.queue_transactions(self.cid, [txn])

    def adopt(self, entries: list[LogEntry], head: eversion_t,
              les: int) -> None:
        """Replace this shard's log with the authoritative one (a stale
        shard rejoining: its data is healed by recovery, its history by
        adoption — reference PGLog::merge_log for the divergent-free
        case)."""
        txn = _txn()
        txn.touch(self.moid)
        txn.omap_clear(self.moid)
        if entries:
            txn.omap_setkeys(self.moid, {
                _omap_key(e): json.dumps(entry_to_wire(e)).encode()
                for e in entries})
        self.log = PGLog()
        for e in sorted(entries, key=lambda e: e.version):
            self.log.add(e)
        self.info.last_update = head
        self.info.last_epoch_started = max(
            self.info.last_epoch_started, les)
        txn.setattr(self.moid, INFO_ATTR,
                    json.dumps(self.info.to_json()).encode())
        self.store.queue_transactions(self.cid, [txn])

    # -- PG split (reference PG::split_into / PGLog::split_out_child:
    #    the parent's log partitions by which child each entry's object
    #    rehashes into; the child inherits the parent's info bounds) ----

    def merge_split(self, entries: list[LogEntry], last_update: eversion_t,
                    les: int) -> None:
        """Adopt split-inherited entries WITHOUT clobbering anything
        this shard already logged (a child shard may have received
        backfill or even new writes before the local parent's split
        sweep ran — unlike `adopt`, which replaces).  The info bounds
        only ratchet up: inheriting the parent's last_update /
        last_epoch_started is what lets child peering fence out shards
        that never saw the parent's history."""
        existing = {_omap_key(e) for e in self.log.entries}
        add = sorted((e for e in entries
                      if _omap_key(e) not in existing),
                     key=lambda e: e.version)
        txn = _txn()
        txn.touch(self.moid)
        if add:
            txn.omap_setkeys(self.moid, {
                _omap_key(e): json.dumps(entry_to_wire(e)).encode()
                for e in add})
            merged = sorted(self.log.entries + add,
                            key=lambda e: e.version)
            newlog = PGLog()
            for e in merged:
                newlog.add(e)
            newlog.tail = self.log.tail
            newlog.can_rollback_to = self.log.can_rollback_to
            newlog.rollforward_to = self.log.rollforward_to
            self.log = newlog
        self.info.last_update = max(self.info.last_update, last_update)
        self.info.last_epoch_started = max(
            self.info.last_epoch_started, les)
        txn.setattr(self.moid, INFO_ATTR,
                    json.dumps(self.info.to_json()).encode())
        self.store.queue_transactions(self.cid, [txn])

    def fold_in(self, entries: list[LogEntry]) -> int:
        """PG-merge log union (the inverse of split_out): adopt a
        dying child's entries WITHOUT moving this shard's peering
        bounds.  Only entries at or below our own last_update union in
        (as recovery history); newer child entries are dropped here —
        their data travels as unlogged backfill instead — because a
        bound ratchet would be non-uniform across the parent's acting
        shards (each folds whichever children IT held) and the peering
        min-last_update rule would roll the ratcheted shards back,
        undoing folded writes as if they were divergent.  Returns the
        number of entries adopted."""
        fold = [e for e in entries
                if e.version <= self.info.last_update]
        if fold:
            self.merge_split(fold, self.info.last_update,
                             self.info.last_epoch_started)
        return len(fold)

    def split_out(self, names: set[str]) -> list[LogEntry]:
        """Drop (and return) the entries whose object moved to a child
        PG.  The parent's last_update is NOT lowered: it still bounds
        every entry the parent ever acked, and the peering min-rule
        needs all parent shards to agree on it."""
        moved = [e for e in self.log.entries if e.oid.name in names]
        if not moved:
            return []
        kept = [e for e in self.log.entries if e.oid.name not in names]
        txn = _txn()
        txn.touch(self.moid)
        txn.omap_rmkeys(self.moid, [_omap_key(e) for e in moved])
        newlog = PGLog()
        for e in kept:
            newlog.add(e)
        newlog.head = self.log.head
        newlog.tail = self.log.tail
        newlog.can_rollback_to = self.log.can_rollback_to
        newlog.rollforward_to = self.log.rollforward_to
        self.log = newlog
        self.store.queue_transactions(self.cid, [txn])
        return moved

    def rollback_to(self, v: eversion_t) -> list[hobject_t]:
        """Undo local entries newer than v.  Pure appends truncate back
        (and restore the prior hinfo xattr); overwrites/deletes restore
        the object generation snapshotted at write time; only legacy
        entries with neither are removed and reported, so the primary's
        recovery rebuilds them from the authoritative shards.
        Returns the oids needing such recovery."""
        from .ec_util import HINFO_KEY

        undone = [e for e in self.log.entries if e.version > v]
        undone.sort(key=lambda e: e.version, reverse=True)
        removed: list[hobject_t] = []
        txn = _txn()
        for e in undone:
            goid = ghobject_t(e.oid, shard=self.shard)
            rb = e.rollback
            has_gen = rb.kept_generation is not None and \
                self.store.exists(self.cid, ghobject_t(
                    e.oid, rb.kept_generation, self.shard))
            if has_gen:
                # the generation IS the pre-entry object (data + attrs)
                gen_goid = ghobject_t(e.oid, rb.kept_generation,
                                      self.shard)
                txn.remove(goid)
                txn.rename(gen_goid, goid)
            elif (e.op is LogOp.MODIFY and rb.pure_append
                    and rb.old_chunk_size is not None):
                if rb.old_chunk_size == 0 and rb.hinfo_old is None:
                    txn.remove(goid)
                else:
                    txn.truncate(goid, rb.old_chunk_size)
                    if rb.hinfo_old is not None:
                        txn.setattr(goid, HINFO_KEY, rb.hinfo_old)
                    else:
                        txn.rmattr(goid, HINFO_KEY)
            else:
                txn.remove(goid)
                if e.oid not in removed:
                    removed.append(e.oid)
            txn.omap_rmkeys(self.moid, [_omap_key(e)])
        self.log.rollforward_to = min(self.log.rollforward_to, v)
        self.log.rollback_to(v)
        self.info.last_update = v
        txn.touch(self.moid)
        txn.setattr(self.moid, INFO_ATTR,
                    json.dumps(self.info.to_json()).encode())
        self.store.queue_transactions(self.cid, [txn])
        return removed


def _txn():
    from ..store.object_store import Transaction
    return Transaction()
