"""PG log: per-PG op journal for recovery and EC rollback.

Re-expresses reference src/osd/PGLog.{h,cc} at the fidelity the EC
pipeline needs: an ordered list of entries keyed by eversion, each
carrying enough rollback state to locally undo it (the reference's
design constraint that EC ops be locally rollbackable —
doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27: append records
the old size, delete keeps the old generation, setattr keeps prior
values), plus the can_rollback_to / rollforward bounds ECBackend
advances in try_finish_rmw (reference ECBackend.cc:2115-2134).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from .types import eversion_t, hobject_t


class LogOp(Enum):
    MODIFY = "modify"
    DELETE = "delete"
    ERROR = "error"


@dataclass
class RollbackInfo:
    """What a shard must remember to undo this entry locally."""
    append_old_size: int | None = None          # size before an append
    old_attrs: dict[str, bytes | None] | None = None  # prior xattr values
    kept_generation: int | None = None          # delete renamed to this gen
    hinfo_old: bytes | None = None              # prior hinfo xattr


@dataclass
class LogEntry:
    version: eversion_t
    oid: hobject_t
    op: LogOp = LogOp.MODIFY
    rollback: RollbackInfo = field(default_factory=RollbackInfo)


class PGLog:
    def __init__(self) -> None:
        self.entries: list[LogEntry] = []
        self.head = eversion_t()            # newest logged
        self.tail = eversion_t()            # oldest kept
        self.can_rollback_to = eversion_t() # entries after this are undoable
        self.rollforward_to = eversion_t()  # entries before this are durable

    def add(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        self.head = entry.version

    def roll_forward_to(self, v: eversion_t) -> list[LogEntry]:
        """Mark entries <= v irrevocable; returns the newly-stable ones
        (whose rollback state may be discarded / old gens trimmed)."""
        newly = [e for e in self.entries
                 if self.rollforward_to < e.version <= v]
        if v > self.rollforward_to:
            self.rollforward_to = v
        if v > self.can_rollback_to:
            self.can_rollback_to = v
        return newly

    def rollback_to(self, v: eversion_t) -> list[LogEntry]:
        """Drop entries newer than v; returns them newest-first so the
        caller can undo their store effects.  Only legal if v >=
        rollforward_to (can't undo what was rolled forward)."""
        assert v >= self.rollforward_to, (v, self.rollforward_to)
        undone = sorted((e for e in self.entries if e.version > v),
                        key=lambda e: e.version, reverse=True)
        self.entries = [e for e in self.entries if e.version <= v]
        self.head = v
        return undone

    def trim(self, to: eversion_t) -> None:
        """Discard entries <= to (reference log trimming)."""
        self.entries = [e for e in self.entries if e.version > to]
        if to > self.tail:
            self.tail = to
