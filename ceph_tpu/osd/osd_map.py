"""OSDMap: the epoch-versioned cluster map.

Re-expresses reference src/osd/OSDMap.{h,cc}: which OSDs exist/are
up/in, their addresses and weights, the pools (`pg_pool_t` with type,
size, pg_num, EC profile, stripe_width), pg_temp overrides, and the
placement queries everything uses — object -> PG -> OSDs
(`pg_to_up_acting_osds`, reference OSDMap.cc:2627, which runs CRUSH and
then applies up/down filtering and overrides).

Incremental maps: `Incremental` records deltas; `apply_incremental`
advances the epoch.  (The mon is the sole author; everyone else applies.)
The delta is produced by structural diff of the committed wire JSON
(`Incremental.diff`) rather than by mutation recording — the reference's
`OSDMap::Incremental` is likewise a new_*/old_* delta encoding, and the
diff construction makes `apply_incremental(full_{e-1}) == full_e`
bit-equal BY CONSTRUCTION for every mutator, present and future, instead
of relying on each mutation site to remember to record itself.
"""

from __future__ import annotations

import json

from dataclasses import dataclass, field, replace

from ..crush import CrushWrapper
from ..crush.hash import crush_hash32
from ..crush.map import CRUSH_ITEM_NONE
from .types import PoolType, pg_t, spg_t


@dataclass
class PGPool:
    """pg_pool_t (reference osd_types.h)."""
    id: int
    name: str
    type: PoolType
    size: int                     # replicas or k+m
    min_size: int
    pg_num: int
    crush_rule: int
    erasure_code_profile: str = ""
    stripe_width: int = 0
    # self-managed snapshot id allocator (reference pg_pool_t snap_seq
    # for SNAP_MODE_SELFMANAGED; the mon allocates ids, clients carry
    # them in per-op SnapContexts) + deleted ids awaiting trim
    # (reference pg_pool_t removed_snaps interval set)
    snap_seq: int = 0
    removed_snaps: list = field(default_factory=list)
    # pg_autoscaler authority (reference pg_pool_t pg_autoscale_mode):
    # "warn" = advisory only (health warning), "on" = the mgr module
    # may issue real pg_num changes (both directions) through the mon
    pg_autoscale_mode: str = "warn"
    # highest pg_num this pool ever had (reference: the role of
    # pg_num_pending/past_intervals history for merges).  Committed in
    # the map so ANY osd — including one that was down across the
    # shrink — can derive which seeds are dying merge children
    # (pg_num <= seed < pg_num_max) and where their data may still
    # sit.  0 means "never resized" (treat as pg_num).
    pg_num_max: int = 0

    def pg_num_ever(self) -> int:
        return max(self.pg_num, self.pg_num_max)

    def is_erasure(self) -> bool:
        return self.type == PoolType.ERASURE


def validate_pg_num_step(cur: int, new: int) -> None:
    """Structural validation for a pg_num change, shared by the mon
    command path and the map mutator (one source of truth for the
    error strings): >= 1, and powers of two on both sides — the
    ps-bits rule (child = hash mod pg_num) only folds exactly when
    both counts are powers of two, in either direction."""
    if new < 1:
        raise ValueError(
            f"pg_num {new} below 1: a pool needs at least one PG")
    if new & (new - 1) or cur & (cur - 1):
        raise ValueError(
            f"pg_num must step between powers of two "
            f"({cur} -> {new}): the ps-bits rule "
            f"(child = hash mod pg_num) only folds exactly when "
            f"both counts are powers of two")


@dataclass
class OSDInfo:
    id: int
    up: bool = False
    in_: bool = True
    weight: float = 1.0           # reweight in [0,1]
    addr: tuple[str, int] | None = None


class OSDMap:
    def __init__(self) -> None:
        self.epoch = 0
        self.osds: dict[int, OSDInfo] = {}
        self.pools: dict[int, PGPool] = {}
        self.pool_ids_by_name: dict[str, int] = {}
        self.crush = CrushWrapper()
        self.pg_temp: dict[pg_t, list[int]] = {}
        # fine-grained balancer overrides (reference pg_upmap_items,
        # OSDMap.h): per-PG [from, to] device substitutions applied to
        # the RAW crush result — unlike pg_temp (a whole acting-set
        # override for peering/backfill), upmap items survive remaps of
        # unrelated devices and compose with CRUSH
        self.pg_upmap_items: dict[pg_t, list[tuple[int, int]]] = {}
        # memo of the raw CRUSH walk per pg — the per-op hot path's
        # expensive part; pure in (crush, pools), so every structural
        # mutator below invalidates.  pg_temp/upmap overlays apply
        # live on top (they may be mutated directly in tests/tools)
        self._pg_cache: dict[pg_t, list[int]] = {}
        self.ec_profiles: dict[str, dict[str, str]] = {}
        # client fencing (reference OSDMap blacklist, consumed by
        # ManagedLock): messenger entity -> expiry unix time.  OSDs
        # reject ops from blacklisted entities with -ESHUTDOWN (the
        # EBLACKLISTED role), closing the in-flight-op window an
        # exclusive-lock steal leaves open.
        self.blacklist: dict[str, float] = {}

    # -- queries ------------------------------------------------------------

    def get_pool(self, pool_id: int) -> PGPool | None:
        return self.pools.get(pool_id)

    def lookup_pool(self, name: str) -> PGPool | None:
        pid = self.pool_ids_by_name.get(name)
        return self.pools.get(pid) if pid is not None else None

    def is_up(self, osd: int) -> bool:
        o = self.osds.get(osd)
        return bool(o and o.up)

    def object_to_pg(self, pool_id: int, name: str, key: str = "") -> pg_t:
        """object name -> pg seed (reference object_locator_to_pg via
        ceph_str_hash + ceph_stable_mod)."""
        pool = self.pools[pool_id]
        h = crush_hash32(key or name)
        return pg_t(pool_id, h % pool.pg_num)

    def _weight_of(self):
        osds = self.osds

        def weight(item: int) -> float:
            if item < 0:
                return 1.0
            o = osds.get(item)
            if o is None or not o.in_:
                return 0.0
            return o.weight
        return weight

    def pg_to_raw_osds(self, pgid: pg_t) -> list[int]:
        hit = self._pg_cache.get(pgid)
        if hit is not None:
            return list(hit)
        pool = self.pools[pgid.pool]
        x = crush_hash32(pgid.pool, pgid.seed)
        out = self.crush.do_rule(pool.crush_rule, x, pool.size,
                                 weight_of=self._weight_of())
        self._pg_cache[pgid] = list(out)
        return out

    def pg_to_raw_upmap_osds(self, pgid: pg_t) -> list[int]:
        """Raw crush result with pg_upmap_items applied, BEFORE any
        up/down filtering — the positional list the balancer diffs
        against (reference _pg_to_raw_osds + _apply_upmap)."""
        raw = self.pg_to_raw_osds(pgid)
        pairs = self.pg_upmap_items.get(pgid)
        if pairs:
            mapping = dict(pairs)
            cand = [mapping.get(d, d) for d in raw]
            live = [d for d in cand if d != CRUSH_ITEM_NONE]
            if len(set(live)) == len(live):
                raw = cand
        return raw

    def pg_to_up_acting_osds(self, pgid: pg_t
                             ) -> tuple[list[int], list[int], int, int]:
        """(up, acting, up_primary, acting_primary) — reference
        OSDMap.cc:2627.  EC pools keep positional NONE holes; replicated
        pools compact them out."""
        pool = self.pools[pgid.pool]
        raw = self.pg_to_raw_upmap_osds(pgid)
        if pool.is_erasure():
            up = [d if d != CRUSH_ITEM_NONE and self.is_up(d)
                  else CRUSH_ITEM_NONE for d in raw]
        else:
            up = [d for d in raw if d != CRUSH_ITEM_NONE and self.is_up(d)]
        acting = self.pg_temp.get(pgid, up)
        up_primary = next((d for d in up if d != CRUSH_ITEM_NONE), -1)
        acting_primary = next(
            (d for d in acting if d != CRUSH_ITEM_NONE), -1)
        return up, acting, up_primary, acting_primary

    def primary_shard(self, pgid: pg_t) -> spg_t | None:
        pool = self.pools[pgid.pool]
        up, acting, _, primary = self.pg_to_up_acting_osds(pgid)
        if primary < 0:
            return None
        if pool.is_erasure():
            return spg_t(pgid, acting.index(primary))
        return spg_t(pgid)

    # -- mutation (mon-side) ------------------------------------------------

    def add_osd(self, osd_id: int, host: str, weight: float = 1.0,
                addr: tuple[str, int] | None = None) -> None:
        self.osds[osd_id] = OSDInfo(osd_id, up=False, in_=True,
                                    weight=1.0, addr=addr)
        self.crush.add_osd(osd_id, weight, host)
        self._pg_cache.clear()

    def set_osd_up(self, osd_id: int, addr: tuple[str, int] | None = None
                   ) -> None:
        o = self.osds[osd_id]
        o.up = True
        if addr:
            o.addr = addr
        self._pg_cache.clear()

    def set_osd_down(self, osd_id: int) -> None:
        if osd_id in self.osds:
            self.osds[osd_id].up = False
        self._pg_cache.clear()

    def set_osd_out(self, osd_id: int) -> None:
        if osd_id in self.osds:
            self.osds[osd_id].in_ = False
        self._pg_cache.clear()

    def set_osd_weight(self, osd_id: int, weight: float) -> None:
        """Reweight in [0,1] (reference `osd reweight`): CRUSH draws
        scale by it, so walking it to 0 backfills every PG off the OSD
        while the daemon stays up to serve as a recovery source."""
        if not 0.0 <= weight <= 1.0:
            raise ValueError(f"weight {weight} not in [0, 1]")
        self.osds[osd_id].weight = weight
        self._pg_cache.clear()

    def remove_osd(self, osd_id: int) -> None:
        """Drop an OSD from the map entirely (reference `osd rm` +
        `osd crush remove`): device, crush bucket membership, and any
        override-table entries naming it."""
        self.osds.pop(osd_id, None)
        self.crush.remove_osd(osd_id)
        self.pg_temp = {pg: v for pg, v in self.pg_temp.items()
                        if osd_id not in v}
        self.pg_upmap_items = {
            pg: pairs for pg, pairs in self.pg_upmap_items.items()
            if all(osd_id not in p for p in pairs)}
        self._pg_cache.clear()

    def create_pool(self, name: str, type_: PoolType, size: int,
                    pg_num: int, crush_rule: int,
                    erasure_code_profile: str = "",
                    stripe_width: int = 0,
                    min_size: int | None = None) -> PGPool:
        pid = max(self.pools, default=0) + 1
        if min_size is None:
            min_size = size - 1 if type_ == PoolType.REPLICATED else size
        pool = PGPool(pid, name, type_, size, min_size, pg_num, crush_rule,
                      erasure_code_profile, stripe_width)
        self.pools[pid] = pool
        self.pool_ids_by_name[name] = pid
        return pool

    def set_pool_pg_num(self, pool_id: int, new_pg_num: int) -> None:
        """Resize a pool's pg_num in EITHER direction (PG split or
        merge; reference OSDMonitor prepare_command pg_num change —
        decrease landed in Nautilus).  Structural validation lives
        here (the mon command path adds cluster-state gating such as
        the split/merge interleave guard); the mutator also keeps the
        override tables consistent: every pg_temp and pg_upmap_items
        entry of the pool is pruned — a resize is a new interval for
        every PG of the pool (parents change content, children are
        born or die), so acting-set and raw-mapping overrides computed
        for the old interval no longer describe anything (reference
        OSDMonitor clean_temps + maybe_remove_pg_upmaps pruning on
        pg_num change)."""
        pool = self.pools[pool_id]
        validate_pg_num_step(pool.pg_num, new_pg_num)
        pool.pg_num_max = max(pool.pg_num_ever(), new_pg_num)
        pool.pg_num = new_pg_num
        self.pg_temp = {pg: v for pg, v in self.pg_temp.items()
                        if pg.pool != pool_id}
        self.pg_upmap_items = {pg: v for pg, v in
                               self.pg_upmap_items.items()
                               if pg.pool != pool_id}
        self._pg_cache.clear()

    def bump_epoch(self) -> int:
        self.epoch += 1
        self._pg_cache.clear()
        return self.epoch

    # -- incremental adoption (subscriber side) -----------------------------

    def canonical(self) -> str:
        """Order-independent canonical serialization — the bit-equality
        yardstick: two maps are the same state iff their canonical
        strings are equal (wire JSON list ordering is insertion-order
        on the mon and id-order after an incremental rebuild; no query
        depends on it)."""
        return json.dumps(map_json_keyed(self.to_json()), sort_keys=True)

    def apply_incremental(self, inc: "Incremental") -> "OSDMap":
        """Advance this map by one committed delta, returning the NEW
        map (the adoption paths replace their map wholesale, like the
        full-map path).  Raises ValueError on an epoch gap — the caller
        falls back to an explicit full-map re-request."""
        if inc.prev != self.epoch:
            raise ValueError(
                f"incremental {inc.prev}->{inc.epoch} does not apply "
                f"to epoch {self.epoch} (gap)")
        keyed = map_json_keyed(self.to_json())
        inc.patch(keyed)
        return OSDMap.from_json(keyed_to_map_json(keyed))

    # -- wire form (mon -> everyone; reference OSDMap::encode) --------------

    def to_json(self) -> dict:
        from ..crush.map import Rule, Step
        crush = self.crush.map
        # every mutable container is COPIED: a to_json snapshot (the
        # mon's committed value, the incremental diff base) must not
        # change underneath when the live map mutates in place
        return {
            "epoch": self.epoch,
            "osds": [[o.id, o.up, o.in_, o.weight, list(o.addr or ())]
                     for o in self.osds.values()],
            "pools": [[p.id, p.name, int(p.type), p.size, p.min_size,
                       p.pg_num, p.crush_rule, p.erasure_code_profile,
                       p.stripe_width, p.snap_seq,
                       list(p.removed_snaps), p.pg_autoscale_mode,
                       p.pg_num_max]
                      for p in self.pools.values()],
            "pg_temp": [[pg.pool, pg.seed, list(osds)]
                        for pg, osds in self.pg_temp.items()],
            "pg_upmap_items": [
                [pg.pool, pg.seed, [list(p) for p in pairs]]
                for pg, pairs in self.pg_upmap_items.items()],
            "ec_profiles": {name: dict(p)
                            for name, p in self.ec_profiles.items()},
            "blacklist": dict(self.blacklist),
            "crush": {
                "devices": [[d.id, d.weight, d.device_class]
                            for d in crush.devices.values()],
                "buckets": [[b.id, b.name, b.type_name, list(b.items),
                             list(b.weights)]
                            for b in crush.buckets.values()],
                "rules": [[r.id, r.name, r.mode,
                           [[s.op, s.num, s.type_name, s.mode, s.item]
                            for s in r.steps]]
                          for r in crush.rules.values()],
                "next_bucket_id": self.crush._next_bucket_id,
                "next_rule_id": self.crush._next_rule_id,
            },
        }

    @classmethod
    def from_json(cls, j: dict) -> "OSDMap":
        from ..crush.map import Bucket, Rule, Step
        m = cls()
        m.epoch = j["epoch"]
        for oid_, up, in_, w, addr in j["osds"]:
            m.osds[oid_] = OSDInfo(oid_, up, in_, w,
                                   tuple(addr) if addr else None)
        for rec in j["pools"]:
            pid, name, t, size, msize, pgn, rule, prof, sw = rec[:9]
            snap_seq = rec[9] if len(rec) > 9 else 0
            removed = list(rec[10]) if len(rec) > 10 else []
            autoscale = rec[11] if len(rec) > 11 else "warn"
            pg_num_max = rec[12] if len(rec) > 12 else 0
            m.pools[pid] = PGPool(pid, name, PoolType(t), size, msize,
                                  pgn, rule, prof, sw,
                                  snap_seq=snap_seq,
                                  removed_snaps=removed,
                                  pg_autoscale_mode=autoscale,
                                  pg_num_max=pg_num_max)
            m.pool_ids_by_name[name] = pid
        for pool, seed, osds in j.get("pg_temp", []):
            m.pg_temp[pg_t(pool, seed)] = osds
        for pool, seed, pairs in j.get("pg_upmap_items", []):
            m.pg_upmap_items[pg_t(pool, seed)] = \
                [tuple(p) for p in pairs]
        m.ec_profiles = dict(j.get("ec_profiles", {}))
        m.blacklist = dict(j.get("blacklist", {}))
        cj = j["crush"]
        cm = m.crush.map
        for did, w, dc in cj["devices"]:
            cm.devices[did] = __import__(
                "ceph_tpu.crush.map", fromlist=["Device"]).Device(did, w, dc)
        for bid, name, tname, items, weights in cj["buckets"]:
            b = Bucket(bid, name, tname, list(items), list(weights))
            cm.buckets[bid] = b
            cm.buckets_by_name[name] = b
        for rid, name, mode, steps in cj["rules"]:
            cm.rules[rid] = Rule(rid, name,
                                 [Step(op=s[0], num=s[1], type_name=s[2],
                                       mode=s[3], item=s[4]) for s in steps],
                                 mode=mode)
        m.crush._next_bucket_id = cj["next_bucket_id"]
        m.crush._next_rule_id = cj["next_rule_id"]
        return m


# -- incremental maps (reference OSDMap::Incremental + the MOSDMap
#    incremental ranges OSDMonitor::send_incremental ships) -----------------
#
# The wire JSON's sections re-keyed as dicts so a delta is a set of
# dict set/del operations and map equality is order-independent.

_KEYED_SECTIONS = ("osds", "pools", "pg_temp", "pg_upmap_items",
                   "ec_profiles", "blacklist", "crush_devices",
                   "crush_buckets", "crush_rules")
_SCALAR_KEYS = ("next_bucket_id", "next_rule_id")


def map_json_keyed(j: dict) -> dict:
    """Canonical keyed form of a full-map wire JSON (extra keys such
    as the piggybacked central config are dropped — they are not map
    state)."""
    crush = j.get("crush", {})
    return {
        "epoch": j["epoch"],
        "osds": {str(rec[0]): list(rec) for rec in j.get("osds", [])},
        "pools": {str(rec[0]): list(rec) for rec in j.get("pools", [])},
        "pg_temp": {f"{pool}.{seed}": [pool, seed, list(osds)]
                    for pool, seed, osds in j.get("pg_temp", [])},
        "pg_upmap_items": {
            f"{pool}.{seed}": [pool, seed,
                               [list(p) for p in pairs]]
            for pool, seed, pairs in j.get("pg_upmap_items", [])},
        "ec_profiles": {name: dict(p)
                        for name, p in j.get("ec_profiles", {}).items()},
        "blacklist": dict(j.get("blacklist", {})),
        "crush_devices": {str(rec[0]): list(rec)
                          for rec in crush.get("devices", [])},
        "crush_buckets": {str(rec[0]): list(rec)
                          for rec in crush.get("buckets", [])},
        "crush_rules": {str(rec[0]): list(rec)
                        for rec in crush.get("rules", [])},
        "next_bucket_id": crush.get("next_bucket_id", -1),
        "next_rule_id": crush.get("next_rule_id", 0),
    }


def keyed_to_map_json(keyed: dict) -> dict:
    """Rebuild a from_json-consumable full-map JSON from the keyed
    form (sections come out id-ordered; nothing reads the order)."""
    def by_id(sec: str) -> list:
        return [keyed[sec][k]
                for k in sorted(keyed[sec], key=lambda s: int(s))]

    def by_pg(sec: str) -> list:
        return [keyed[sec][k] for k in sorted(
            keyed[sec], key=lambda s: tuple(map(int, s.split("."))))]

    return {
        "epoch": keyed["epoch"],
        "osds": by_id("osds"),
        "pools": by_id("pools"),
        "pg_temp": by_pg("pg_temp"),
        "pg_upmap_items": by_pg("pg_upmap_items"),
        "ec_profiles": keyed["ec_profiles"],
        "blacklist": keyed["blacklist"],
        "crush": {
            "devices": by_id("crush_devices"),
            "buckets": by_id("crush_buckets"),
            "rules": by_id("crush_rules"),
            "next_bucket_id": keyed["next_bucket_id"],
            "next_rule_id": keyed["next_rule_id"],
        },
    }


def apply_inc_chain(osdmap: OSDMap, incs: list) -> OSDMap | None:
    """Apply a published delta chain (Incremental wire JSONs, oldest
    first) on top of `osdmap`: already-applied epochs are skipped
    (duplicate delivery), and None means an epoch GAP — the caller
    must fall back to an explicit full-map request.  The one applier
    shared by OSD, objecter, and mgr."""
    m = osdmap
    try:
        for j in incs:
            inc = Incremental.from_json(j)
            if inc.epoch <= m.epoch:
                continue
            m = m.apply_incremental(inc)
    except ValueError:
        return None
    return m


@dataclass
class Incremental:
    """One committed epoch's delta: apply on top of epoch `prev` to
    reach epoch `epoch`.  Sections carry full replacement records for
    changed/added keys and a removal list — the shape of the
    reference's new_*/old_* maps in OSDMap::Incremental."""
    epoch: int
    prev: int
    sets: dict = field(default_factory=dict)   # section -> {key: record}
    dels: dict = field(default_factory=dict)   # section -> [keys]

    @classmethod
    def diff(cls, old_j: dict, new_j: dict) -> "Incremental":
        """Structural diff of two full-map wire JSONs (old -> new)."""
        ok, nk = map_json_keyed(old_j), map_json_keyed(new_j)
        sets: dict = {}
        dels: dict = {}
        for sec in _KEYED_SECTIONS:
            o, n = ok[sec], nk[sec]
            changed = {k: v for k, v in n.items() if o.get(k) != v}
            gone = sorted(k for k in o if k not in n)
            if changed:
                sets[sec] = changed
            if gone:
                dels[sec] = gone
        scalars = {k: nk[k] for k in _SCALAR_KEYS if ok[k] != nk[k]}
        if scalars:
            sets["_scalars"] = scalars
        return cls(epoch=nk["epoch"], prev=ok["epoch"],
                   sets=sets, dels=dels)

    def patch(self, keyed: dict) -> None:
        """Apply in place onto a keyed full-map form."""
        for sec, keys in self.dels.items():
            d = keyed.get(sec)
            if d is not None:
                for k in keys:
                    d.pop(k, None)
        for sec, vals in self.sets.items():
            if sec == "_scalars":
                keyed.update(vals)
            else:
                keyed.setdefault(sec, {}).update(vals)
        keyed["epoch"] = self.epoch

    def to_json(self) -> dict:
        return {"epoch": self.epoch, "prev": self.prev,
                "set": self.sets, "del": self.dels}

    @classmethod
    def from_json(cls, j: dict) -> "Incremental":
        return cls(epoch=j["epoch"], prev=j["prev"],
                   sets=dict(j.get("set", {})),
                   dels=dict(j.get("del", {})))
