"""Scrub: background cross-shard consistency checking + repair.

Re-expresses the reference's scrub machinery (src/osd/PG.cc scrub
methods, PGBackend::be_scan_list PGBackend.cc:571, ScrubStore, and the
EC design note in doc/dev/osd_internals/erasure_coding/ecbackend.rst
"Scrub": EC shards self-check their local cumulative crc32c against the
stored hinfo, so a primary can detect bit rot without decoding):

  shallow scrub — every shard present, sizes consistent, hinfo attrs
                  agree across shards
  deep scrub    — additionally read each shard and verify its crc32c
                  against the hinfo entry
  repair        — reconstruct bad/missing shards from survivors via the
                  EC decode path and write them back

Works against the ShardBackend seam, so the same code scrubs a local
MemStore PG (tests) and a messenger-backed PG (daemon asok command).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..common import crc32c as _crc
from .ec_backend import ECBackend
from .ec_transaction import shard_oid
from .ec_util import HINFO_KEY
from .types import hobject_t


@dataclass
class ScrubError:
    oid: hobject_t
    shard: int
    kind: str          # missing | size_mismatch | crc_mismatch | hinfo
    detail: str = ""


@dataclass
class ScrubResult:
    objects: int = 0
    errors: list[ScrubError] = field(default_factory=list)
    repaired: list[ScrubError] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.errors


def scrub_object(backend: ECBackend, oid: hobject_t,
                 deep: bool = True) -> list[ScrubError]:
    from .ec_util import CHUNK_CRC_KEY, HashInfo
    errors: list[ScrubError] = []
    n = backend.n
    hinfos = {}
    sizes = {}
    chunk_crcs = {}
    for s in range(n):
        sizes[s] = backend.shards.stat(s, oid)
        attrs = backend.shards.get_attrs(s, oid) or {}
        raw = attrs.get(HINFO_KEY)
        hinfos[s] = HashInfo.decode(raw) if raw else None
        cc = attrs.get(CHUNK_CRC_KEY)
        chunk_crcs[s] = int.from_bytes(cc, "little") if cc else None
    present = [s for s in range(n) if sizes[s] is not None]
    if not present:
        return errors
    if all(sizes[s] == 0 for s in present) and \
            all(hinfos[s] is None for s in present):
        # pure-metadata object (snapdir, SS-only head): no payload to
        # checksum, attrs are replicated by the write path
        return errors
    for s in range(n):
        if sizes[s] is None:
            errors.append(ScrubError(oid, s, "missing"))
    # size consistency
    size_counts: dict[int, int] = {}
    for s in present:
        size_counts[sizes[s]] = size_counts.get(sizes[s], 0) + 1
    majority_size = max(size_counts, key=size_counts.get)
    for s in present:
        if sizes[s] != majority_size:
            errors.append(ScrubError(
                oid, s, "size_mismatch",
                f"{sizes[s]} != majority {majority_size}"))
    # hinfo agreement (hinfo is replicated on every shard)
    ref_hinfo = None
    for s in present:
        if hinfos[s] is not None:
            ref_hinfo = hinfos[s]
            break
    for s in present:
        if hinfos[s] is None:
            errors.append(ScrubError(oid, s, "hinfo", "missing hinfo"))
        elif ref_hinfo is not None and ref_hinfo.crc_valid and \
                hinfos[s].cumulative_shard_hashes != \
                ref_hinfo.cumulative_shard_hashes:
            errors.append(ScrubError(oid, s, "hinfo",
                                     "hinfo disagrees with peers"))
    if deep and ref_hinfo is not None and \
            ref_hinfo.total_chunk_size == majority_size:
        import threading
        done = {}
        ev = threading.Event()

        def on_done(shard, data, _box=done):
            _box[shard] = data
            if len(_box) >= len(present):
                ev.set()

        for s in present:
            backend.shards.sub_read(s, oid, 0, majority_size, on_done)
        ev.wait(timeout=30)
        for s in present:
            data = done.get(s)
            if data is None:
                continue
            got = _crc.crc32c(np.asarray(data).tobytes(), 0xFFFFFFFF)
            # integrity source: cumulative hinfo for append-only
            # objects; the shard's self-maintained chunk_crc once an
            # overwrite invalidated the hinfo (crc_valid also covers
            # legacy blobs persisted before the sticky flag existed)
            if not ref_hinfo.crc_valid:
                want = chunk_crcs[s]
                if want is None:
                    errors.append(ScrubError(
                        oid, s, "crc_source",
                        "overwritten object lacks chunk_crc"))
                    continue
            else:
                want = ref_hinfo.get_chunk_hash(s)
            if got != want:
                errors.append(ScrubError(
                    oid, s, "crc_mismatch", f"{got:#x} != {want:#x}"))
    return errors


def scrub_pg(backend: ECBackend, oids: list[hobject_t],
             deep: bool = True, repair: bool = False) -> ScrubResult:
    result = ScrubResult()
    for oid in oids:
        result.objects += 1
        errors = scrub_object(backend, oid, deep)
        if errors and repair:
            bad_shards = sorted({e.shard for e in errors
                                 if e.kind in ("missing", "crc_mismatch",
                                               "size_mismatch")})
            if bad_shards and len(bad_shards) <= backend.m:
                _repair_shards(backend, oid, bad_shards)
                still = scrub_object(backend, oid, deep)
                if not still:
                    result.repaired.extend(errors)
                    continue
                errors = still
        result.errors.extend(errors)
    return result


def _repair_shards(backend: ECBackend, oid: hobject_t,
                   bad_shards: list[int]) -> None:
    """Rebuild bad shards from the good ones and write them back
    (reference repair path: recovery reconstruct + push)."""
    from ..store.object_store import Transaction
    hinfo = backend._get_hinfo(oid)
    # read all good shards
    good = [s for s in range(backend.n) if s not in bad_shards]
    chunk_len = None
    for s in good:
        st = backend.shards.stat(s, oid)
        if st is not None:
            chunk_len = st
            break
    if chunk_len is None:
        return
    import threading
    dense = np.zeros((backend.n, chunk_len), dtype=np.uint8)
    got: set[int] = set()
    counted = {"n": 0}
    ev = threading.Event()

    def on_done(shard, data):
        if data is not None:
            dense[shard] = data
            got.add(shard)
        counted["n"] += 1
        if counted["n"] >= len(good):
            ev.set()

    for s in good:
        backend.shards.sub_read(s, oid, 0, chunk_len, on_done)
    ev.wait(timeout=30)
    if len(got) < backend.k:
        return
    erasures = [s for s in range(backend.n) if s not in got]
    rebuilt = backend.ec_impl.decode_chunks(dense, erasures)
    from .ec_util import recovery_attrs
    for s in bad_shards:
        txn = Transaction()
        goid = shard_oid(oid, s)
        txn.remove(goid)
        txn.write(goid, 0, rebuilt[s])
        txn.setattrs(goid, recovery_attrs(hinfo, rebuilt[s]))
        backend.shards.sub_write(s, txn, lambda _s: None)
