"""Scrub: background cross-shard consistency checking + repair.

Re-expresses the reference's scrub machinery (src/osd/PG.cc scrub
methods, PGBackend::be_scan_list PGBackend.cc:571, ScrubStore, and the
EC design note in doc/dev/osd_internals/erasure_coding/ecbackend.rst
"Scrub": EC shards self-check their local cumulative crc32c against the
stored hinfo, so a primary can detect bit rot without decoding):

  shallow scrub — every shard present, sizes consistent, hinfo attrs
                  agree across shards
  deep scrub    — additionally read each shard and verify its crc32c
                  against the hinfo entry
  repair        — reconstruct bad/missing shards from survivors via the
                  EC decode path and write them back

TPU-first deep scrub (docs/PIPELINE.md): objects are walked in chunks;
a chunk's shard reads all fan out through `sub_read_batch` (one batched
fan-out per object instead of n sequential RPCs, every object's reads
in flight together), and every shard of the chunk is checksummed by ONE
device launch (crc32c_linear.crc32c_rows_device — the same GF(2) L
formulation the fused write kernel uses) instead of per-object host
crc32c.  CPU-only platforms fall back to the host hash; the split is
surfaced as scrub_device_bytes / scrub_host_bytes perf counters.

Works against the ShardBackend seam, so the same code scrubs a local
MemStore PG (tests) and a messenger-backed PG (daemon asok command).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..common import crc32c as _crc
from .ec_backend import ECBackend
from .ec_transaction import shard_oid
from .ec_util import CHUNK_CRC_KEY, HINFO_KEY, HashInfo
from .types import hobject_t

# shard bytes per deep-scrub chunk (reads batched + one crc launch)
SCRUB_CHUNK_BYTES = 64 << 20


@dataclass
class ScrubError:
    oid: hobject_t
    shard: int
    kind: str          # missing | size_mismatch | crc_mismatch | hinfo
    detail: str = ""


@dataclass
class ScrubResult:
    objects: int = 0
    errors: list[ScrubError] = field(default_factory=list)
    repaired: list[ScrubError] = field(default_factory=list)
    device_bytes: int = 0
    host_bytes: int = 0

    @property
    def clean(self) -> bool:
        return not self.errors


@dataclass
class _ObjMeta:
    """Shallow-scrub view of one object + what deep verify needs."""
    oid: hobject_t
    sizes: dict[int, int | None]
    hinfos: dict[int, HashInfo | None]
    chunk_crcs: dict[int, int | None]
    present: list[int]
    majority: int = 0
    ref_hinfo: HashInfo | None = None
    errors: list[ScrubError] = field(default_factory=list)
    deep: bool = False          # deep verify applicable


def _use_device_default() -> bool:
    """Device crc only off the CPU backend (the formulation itself is
    pure jnp and CPU-capable — tests force it — but on CPU-only
    platforms the host table/native path is the faster fallback)."""
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — no jax at all: host fallback
        return False


def _collect_meta(backend: ECBackend, oid: hobject_t,
                  deep: bool) -> _ObjMeta | None:
    """Shallow checks (presence / sizes / hinfo agreement) for one
    object; returns None for wholly-absent objects."""
    n = backend.n
    meta = _ObjMeta(oid, {}, {}, {}, [])
    for s in range(n):
        meta.sizes[s] = backend.shards.stat(s, oid)
        attrs = backend.shards.get_attrs(s, oid) or {}
        raw = attrs.get(HINFO_KEY)
        meta.hinfos[s] = HashInfo.decode(raw) if raw else None
        cc = attrs.get(CHUNK_CRC_KEY)
        meta.chunk_crcs[s] = int.from_bytes(cc, "little") if cc else None
    meta.present = [s for s in range(n) if meta.sizes[s] is not None]
    if not meta.present:
        return None
    if all(meta.sizes[s] == 0 for s in meta.present) and \
            all(meta.hinfos[s] is None for s in meta.present):
        # pure-metadata object (snapdir, SS-only head): no payload to
        # checksum, attrs are replicated by the write path
        return meta
    errors = meta.errors
    for s in range(n):
        if meta.sizes[s] is None:
            errors.append(ScrubError(oid, s, "missing"))
    # size consistency
    size_counts: dict[int, int] = {}
    for s in meta.present:
        size_counts[meta.sizes[s]] = size_counts.get(meta.sizes[s], 0) + 1
    meta.majority = max(size_counts, key=size_counts.get)
    for s in meta.present:
        if meta.sizes[s] != meta.majority:
            errors.append(ScrubError(
                oid, s, "size_mismatch",
                f"{meta.sizes[s]} != majority {meta.majority}"))
    # hinfo agreement (hinfo is replicated on every shard)
    for s in meta.present:
        if meta.hinfos[s] is not None:
            meta.ref_hinfo = meta.hinfos[s]
            break
    for s in meta.present:
        if meta.hinfos[s] is None:
            errors.append(ScrubError(oid, s, "hinfo", "missing hinfo"))
        elif meta.ref_hinfo is not None and meta.ref_hinfo.crc_valid and \
                meta.hinfos[s].cumulative_shard_hashes != \
                meta.ref_hinfo.cumulative_shard_hashes:
            errors.append(ScrubError(oid, s, "hinfo",
                                     "hinfo disagrees with peers"))
    meta.deep = bool(
        deep and meta.ref_hinfo is not None and
        meta.ref_hinfo.total_chunk_size == meta.majority)
    return meta


def _deep_read_chunk(backend: ECBackend, metas: list[_ObjMeta]
                     ) -> dict[tuple[hobject_t, int], np.ndarray]:
    """Fan out ALL shard reads of a scrub chunk through
    sub_read_batch (one batched fan-out per object, every object's
    fan-out issued before any wait) and gather the replies."""
    data: dict[tuple[hobject_t, int], np.ndarray] = {}
    lock = threading.Lock()
    ev = threading.Event()
    expect = sum(len(m.present) for m in metas if m.deep)
    got = {"n": 0}
    if not expect:
        return data

    def make_cb(oid):
        def on_done(shard, d):
            with lock:
                if d is not None:
                    data[(oid, shard)] = d
                got["n"] += 1
                fire = got["n"] >= expect
            if fire:
                ev.set()
        on_done.loop_safe = True      # store + Event.set only
        return on_done

    for m in metas:
        if not m.deep:
            continue
        backend.shards.sub_read_batch(
            [(s, m.oid, 0, m.majority) for s in m.present],
            make_cb(m.oid))
    # the old per-object path gave EACH object a 30 s read window; a
    # whole chunk's fan-out gets a deadline that scales with it
    ev.wait(timeout=max(30.0, 0.05 * expect))
    with lock:
        return dict(data)


def _verify_chunk(metas: list[_ObjMeta],
                  data: dict[tuple[hobject_t, int], np.ndarray],
                  use_device: bool, perf=None,
                  result: ScrubResult | None = None
                  ) -> list[ScrubError]:
    """Deep verify of one chunk: ONE device launch checksums every
    shard of every object (variable lengths: front-pad-free L combine,
    see crc32c_linear.crc32c_rows_device), or the host fold when the
    platform is CPU-only."""
    errors: list[ScrubError] = []
    rows: list[np.ndarray] = []
    owners: list[tuple[_ObjMeta, int, int]] = []   # meta, shard, want
    for m in metas:
        if not m.deep:
            continue
        for s in m.present:
            d = data.get((m.oid, s))
            if d is None:
                # a present (stat'd) shard whose read never answered
                # must NOT silently count as verified — a timed-out
                # chunk read would otherwise report the PG clean
                errors.append(ScrubError(
                    m.oid, s, "read_error", "deep-read unanswered"))
                continue
            # integrity source: cumulative hinfo for append-only
            # objects; the shard's self-maintained chunk_crc once an
            # overwrite invalidated the hinfo (crc_valid also covers
            # legacy blobs persisted before the sticky flag existed)
            if not m.ref_hinfo.crc_valid:
                want = m.chunk_crcs[s]
                if want is None:
                    errors.append(ScrubError(
                        m.oid, s, "crc_source",
                        "overwritten object lacks chunk_crc"))
                    continue
            else:
                want = m.ref_hinfo.get_chunk_hash(s)
            rows.append(np.asarray(d, dtype=np.uint8))
            owners.append((m, s, want))
    if not rows:
        return errors
    nbytes = sum(r.size for r in rows)
    seeds = [0xFFFFFFFF] * len(rows)
    if use_device:
        from ..common.util import next_pow2
        from ..ops import crc32c_linear as cl
        from ..ops.profiler import device_profiler
        # flight recorder: the deep-scrub CRC launch is a device
        # launch like any encode — ledgered with an (approximate:
        # pow2 of rows/bytes, the jit axes) bucket key
        prof = device_profiler()
        rec = prof.begin("scrub_crc", codec="crc32c_rows",
                         runs=len(rows), nbytes=nbytes)
        got = cl.crc32c_rows_device(rows, seeds)
        # synchronous call: the submit clock (begin -> here) covers
        # dispatch + compile + execution; device_s stays 0 so the
        # wall is counted ONCE (lat_launch_submit), not twice
        prof.submitted(rec, f"s:crc:n{next_pow2(len(rows))}"
                            f":w{next_pow2(nbytes)}", path="device")
        prof.materialized(rec, 0.0)
        # honest attribution: only full SCRUB_BLOCK bodies ride the
        # device launch; sub-block tails (and rows shorter than one
        # block) fold on host inside crc32c_rows_device
        dev_bytes = sum(r.size - r.size % cl.SCRUB_BLOCK for r in rows)
        host_bytes = nbytes - dev_bytes
        if perf:
            perf.inc("ec_scrub_device_bytes", dev_bytes)
            perf.inc("ec_scrub_host_bytes", host_bytes)
        if result is not None:
            result.device_bytes += dev_bytes
            result.host_bytes += host_bytes
    else:
        got = [_crc.crc32c(r.tobytes(), 0xFFFFFFFF) for r in rows]
        if perf:
            perf.inc("ec_scrub_host_bytes", nbytes)
        if result is not None:
            result.host_bytes += nbytes
    for (m, s, want), g in zip(owners, got):
        if g != want:
            errors.append(ScrubError(
                m.oid, s, "crc_mismatch", f"{g:#x} != {want:#x}"))
    return errors


def scrub_object(backend: ECBackend, oid: hobject_t,
                 deep: bool = True,
                 use_device: bool | None = None) -> list[ScrubError]:
    """Single-object scrub (repair re-checks and unit tests); the PG
    walk goes through scrub_pg's chunked/batched path."""
    if use_device is None:
        use_device = _use_device_default()
    meta = _collect_meta(backend, oid, deep)
    if meta is None:
        return []
    errors = list(meta.errors)
    if meta.deep:
        data = _deep_read_chunk(backend, [meta])
        errors.extend(_verify_chunk([meta], data, use_device,
                                    perf=backend.perf))
    return errors


def scrub_pg(backend: ECBackend, oids: list[hobject_t],
             deep: bool = True, repair: bool = False,
             chunk_bytes: int = SCRUB_CHUNK_BYTES,
             use_device: bool | None = None) -> ScrubResult:
    if use_device is None:
        use_device = _use_device_default()
    result = ScrubResult()
    perf = backend.perf
    chunk: list[_ObjMeta] = []
    budget = 0

    def flush_chunk():
        nonlocal chunk, budget
        if not chunk:
            return
        data = _deep_read_chunk(backend, chunk) if deep else {}
        deep_errors = _verify_chunk(chunk, data, use_device,
                                    perf=perf, result=result) \
            if deep else []
        by_oid: dict[hobject_t, list[ScrubError]] = {}
        for e in deep_errors:
            by_oid.setdefault(e.oid, []).append(e)
        for m in chunk:
            errors = m.errors + by_oid.get(m.oid, [])
            if errors and repair:
                bad_shards = sorted({e.shard for e in errors
                                     if e.kind in ("missing",
                                                   "crc_mismatch",
                                                   "size_mismatch")})
                if bad_shards and len(bad_shards) <= backend.m:
                    _repair_shards(backend, m.oid, bad_shards)
                    still = scrub_object(backend, m.oid, deep,
                                         use_device=use_device)
                    if not still:
                        result.repaired.extend(errors)
                        continue
                    errors = still
            result.errors.extend(errors)
        chunk = []
        budget = 0

    for oid in oids:
        result.objects += 1
        meta = _collect_meta(backend, oid, deep)
        if meta is None:
            continue
        chunk.append(meta)
        if meta.deep:
            budget += meta.majority * len(meta.present)
        if budget >= chunk_bytes:
            flush_chunk()
    flush_chunk()
    return result


def _repair_shards(backend: ECBackend, oid: hobject_t,
                   bad_shards: list[int]) -> None:
    """Rebuild bad shards from the good ones and write them back
    (reference repair path: recovery reconstruct + push)."""
    from ..store.object_store import Transaction
    hinfo = backend._get_hinfo(oid)
    # read all good shards
    good = [s for s in range(backend.n) if s not in bad_shards]
    chunk_len = None
    for s in good:
        st = backend.shards.stat(s, oid)
        if st is not None:
            chunk_len = st
            break
    if chunk_len is None:
        return
    dense = np.zeros((backend.n, chunk_len), dtype=np.uint8)
    got: set[int] = set()
    counted = {"n": 0}
    ev = threading.Event()

    def on_done(shard, data):
        if data is not None:
            dense[shard] = data
            got.add(shard)
        counted["n"] += 1
        if counted["n"] >= len(good):
            ev.set()
    on_done.loop_safe = True

    backend.shards.sub_read_batch(
        [(s, oid, 0, chunk_len) for s in good], on_done)
    ev.wait(timeout=30)
    if len(got) < backend.k:
        return
    erasures = [s for s in range(backend.n) if s not in got]
    rebuilt = backend.ec_impl.decode_chunks(dense, erasures)
    from .ec_util import recovery_attrs
    for s in bad_shards:
        txn = Transaction()
        goid = shard_oid(oid, s)
        txn.remove(goid)
        txn.write(goid, 0, rebuilt[s])
        txn.setattrs(goid, recovery_attrs(hinfo, rebuilt[s]))
        backend.shards.sub_write(s, txn, lambda _s: None)
