"""Op scheduler: QoS between client / recovery / scrub / tenant work.

Re-expresses reference src/osd/scheduler/ (OpScheduler.cc:24
make_scheduler): a pluggable queue the OSD's worker shards pull from,
either weighted-priority (WPQ) or an mClock-style
reservation/weight/limit dequeuer (src/osd/scheduler/mClockScheduler.h,
src/dmclock submodule).  The mClock here implements the core dmclock
idea — per-class virtual tags from (reservation, weight, limit) — not
the full distributed protocol.

Observability (docs/QOS.md): every enqueue/dequeue, the phase that
served it (reservation / weighted proportional / work-conserving
fallback) and the per-class queue wait are counted — into the
scheduler's own `stats` dict always, and into a PerfCounters set
(`mclock_*` u64s + `lat_qwait_<class>` histograms) when one is wired,
so `perf dump` / `dump_latencies` / the prometheus exporter can answer
"who waited, and which phase served whom" without touching the
scheduler.  The dequeue clock is injectable (`now=`) so tag math is
unit-testable and the load harness can drive it in virtual time.
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass(order=True)
class _WPQItem:
    sort_key: tuple
    item: Any = field(compare=False)


class WeightedPriorityQueue:
    """Strict-then-weighted priorities (reference WeightedPriorityQueue):
    strict items first; others dequeued proportionally to priority."""

    def __init__(self):
        self._strict: list = []
        self._heap: list[_WPQItem] = []
        self._counter = itertools.count()
        self._vclock = 0.0

    def enqueue(self, item, priority: int = 63, strict: bool = False,
                **_):
        if strict:
            self._strict.append((priority, next(self._counter), item))
            self._strict.sort(key=lambda t: (-t[0], t[1]))
        else:
            # virtual finish time ~ 1/priority spacing
            self._vclock += 1.0
            key = (self._vclock / max(priority, 1), next(self._counter))
            heapq.heappush(self._heap, _WPQItem(key, item))

    def dequeue(self, now: float | None = None):
        if self._strict:
            return self._strict.pop(0)[2]
        if self._heap:
            return heapq.heappop(self._heap).item
        return None

    def empty(self) -> bool:
        return not self._strict and not self._heap

    def __len__(self):
        return len(self._strict) + len(self._heap)


@dataclass
class ClientProfile:
    """dmclock (reservation, weight, limit) triple per op class."""
    reservation: float = 0.0   # ops/sec guaranteed
    weight: float = 1.0        # proportional share
    limit: float = 0.0         # ops/sec cap (0 = none)


# Named presets (reference osd_mclock_profile: the shipped profiles
# trade client latency against background-work progress; docs/QOS.md).
MCLOCK_PROFILES: dict[str, dict[str, ClientProfile]] = {
    "balanced": {
        "client": ClientProfile(reservation=100.0, weight=2.0),
        "recovery": ClientProfile(reservation=10.0, weight=1.0,
                                  limit=500.0),
        "scrub": ClientProfile(reservation=5.0, weight=0.5, limit=200.0),
    },
    "high_client_ops": {
        "client": ClientProfile(reservation=200.0, weight=4.0),
        "recovery": ClientProfile(reservation=5.0, weight=1.0,
                                  limit=100.0),
        "scrub": ClientProfile(reservation=2.0, weight=0.5, limit=50.0),
    },
    "high_recovery_ops": {
        "client": ClientProfile(reservation=50.0, weight=2.0),
        "recovery": ClientProfile(reservation=50.0, weight=2.0),
        "scrub": ClientProfile(reservation=5.0, weight=1.0, limit=200.0),
    },
}


def parse_custom_profile(spec: str) -> dict[str, ClientProfile]:
    """'class:res,wgt,lim;...' -> {class: ClientProfile}.  The runtime
    override format of osd_mclock_custom_profile — also how tenant
    classes (which the schema can't predeclare) get their triples."""
    out: dict[str, ClientProfile] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        cls, _, triple = entry.partition(":")
        cls = cls.strip()
        parts = [p.strip() for p in triple.split(",")]
        if not cls or len(parts) != 3:
            raise ValueError(
                f"bad mclock profile entry {entry!r} "
                f"(want 'class:res,wgt,lim')")
        res, wgt, lim = (float(p) for p in parts)
        # NaN slips past every <=/< guard and then poisons the tag
        # comparisons (a NaN-weighted class silently starves)
        if not all(math.isfinite(x) for x in (res, wgt, lim)):
            raise ValueError(f"non-finite rate in {entry!r}")
        if wgt <= 0:
            raise ValueError(f"mclock weight must be > 0 in {entry!r}")
        if res < 0 or lim < 0:
            raise ValueError(f"negative rate in {entry!r}")
        if 0 < lim < res:
            # the reservation phase ignores limit tags, so a cap
            # below the guarantee would silently never bind (the
            # reference dmclock rejects limit < reservation too)
            raise ValueError(
                f"limit {lim} < reservation {res} in {entry!r}")
        out[cls] = ClientProfile(res, wgt, lim)
    return out


def profiles_from_conf(conf) -> dict[str, ClientProfile]:
    """Resolve the effective per-class profiles from config:
    osd_mclock_profile names the preset ('custom' starts from
    'balanced'), osd_mclock_custom_profile overrides per class on
    top (reference: mclock profile options expand the same way)."""
    name = str(conf.get("osd_mclock_profile"))
    base = MCLOCK_PROFILES.get(name, MCLOCK_PROFILES["balanced"])
    profiles = {c: ClientProfile(p.reservation, p.weight, p.limit)
                for c, p in base.items()}
    spec = str(conf.get("osd_mclock_custom_profile"))
    if spec:
        profiles.update(parse_custom_profile(spec))
    return profiles


# internal background classes: never accepted from the wire — a client
# declaring qos="recovery" would ride (and distort the accounting of)
# the background class's reservation/limit instead of its own
WIRE_BLOCKED_CLASSES = frozenset({"recovery", "scrub"})


def _zero_stats() -> dict:
    return {"queued": 0, "dequeued": 0, "reservation_served": 0,
            "proportional_served": 0, "fallback_served": 0,
            "wait_sum": 0.0, "wait_max": 0.0}


class MClockScheduler:
    """Single-node dmclock: tag ops with reservation/proportional virtual
    times, serve reservation-eligible first, then by weight, respecting
    limits (reference mClockScheduler defaults: client/recovery/scrub
    classes).

    Tag math per class c with profile (res, wgt, lim):
      reservation tag  r[c]: serve when r[c] <= now, then
                       r[c] = max(r[c], now) + 1/res   (wall clock)
      limit tag        l[c]: proportional phase skips while l[c] > now;
                       l[c] = max(l[c], now) + 1/lim on EVERY serve
      proportional tag p[c]: WFQ virtual time — smallest p wins, then
                       p[c] = max(p[c], vtime) + 1/wgt; a class that
                       wakes from idle is anchored at the current
                       vtime (no banked credit, no stale penalty)
    Limits only bind under contention: when nothing is reservation-
    eligible and every backlogged class is limit-capped, the fallback
    phase serves the lowest proportional tag anyway (work conserving,
    as in dmclock).
    """

    DEFAULT_PROFILES = MCLOCK_PROFILES["balanced"]

    def __init__(self, profiles: dict[str, ClientProfile] | None = None,
                 perf=None):
        self.profiles = {
            c: ClientProfile(p.reservation, p.weight, p.limit)
            for c, p in (profiles or self.DEFAULT_PROFILES).items()}
        self.perf = perf
        self._queues: dict[str, list] = {}
        self._r_tags: dict[str, float] = {}
        self._l_tags: dict[str, float] = {}
        self._p_tags: dict[str, float] = {}
        self._vtime = 0.0
        self._counter = itertools.count()
        self.stats: dict[str, dict] = {}
        self.last_phase: str | None = None
        for c in self.profiles:
            self._ensure_class(c)

    # -- class/profile management -------------------------------------------

    def _ensure_class(self, op_class: str) -> None:
        if op_class in self._queues:
            return
        self._queues[op_class] = []
        self.profiles.setdefault(op_class, ClientProfile())
        self._r_tags[op_class] = 0.0
        self._l_tags[op_class] = 0.0
        # anchor at the current virtual time: a class born mid-run
        # competes from here, not from the epoch
        self._p_tags[op_class] = self._vtime
        self.stats[op_class] = _zero_stats()

    def set_profile(self, op_class: str, profile: ClientProfile) -> None:
        """Runtime (reservation, weight, limit) update for one class."""
        self._ensure_class(op_class)
        self.profiles[op_class] = profile

    def set_profiles(self, profiles: dict[str, ClientProfile]) -> None:
        """Runtime profile swap (mon `osd mclock profile set` landing
        via the config observer).  Queued items stay queued; classes
        the new profile set doesn't name keep running on the default
        best-effort triple."""
        for c, p in profiles.items():
            self.set_profile(c, p)
        for c in self._queues:
            if c not in profiles:
                self.profiles[c] = ClientProfile()

    def apply_conf(self, conf) -> None:
        self.set_profiles(profiles_from_conf(conf))

    # -- queue ops ----------------------------------------------------------

    def enqueue(self, item, op_class: str = "client",
                now: float | None = None, **_):
        now = time.monotonic() if now is None else now
        self._ensure_class(op_class)
        self._queues[op_class].append((next(self._counter), now, item))
        self.stats[op_class]["queued"] += 1
        if self.perf is not None:
            self.perf.dinc(f"mclock_queued_{op_class}")

    def _pick(self, now: float) -> tuple[str | None, str]:
        # 1: reservation phase — any class behind its reservation tag
        best = None
        for c, q in self._queues.items():
            if not q:
                continue
            if self.profiles[c].reservation > 0 and \
                    self._r_tags[c] <= now:
                if best is None or self._r_tags[c] < self._r_tags[best]:
                    best = c
        if best is not None:
            return best, "reservation"
        # 2: proportional phase by weight tags (limit-respecting)
        for c, q in self._queues.items():
            if not q:
                continue
            if self.profiles[c].limit > 0 and self._l_tags[c] > now:
                continue
            if best is None or self._p_tags[c] < self._p_tags[best]:
                best = c
        if best is not None:
            return best, "proportional"
        # 3: work-conserving fallback — nothing reservation-eligible
        # and every backlogged class is ahead of its cap; serve the
        # lowest proportional tag anyway (limits only bind under
        # contention, as in dmclock)
        for c, q in self._queues.items():
            if not q:
                continue
            if best is None or self._p_tags[c] < self._p_tags[best]:
                best = c
        return best, "fallback"

    def dequeue(self, now: float | None = None):
        now = time.monotonic() if now is None else now
        best, phase = self._pick(now)
        if best is None:
            return None
        prof = self.profiles[best]
        if phase == "reservation":
            self._r_tags[best] = max(self._r_tags[best], now) + \
                1.0 / prof.reservation
        else:
            start = max(self._p_tags[best], self._vtime)
            self._vtime = start
            self._p_tags[best] = start + 1.0 / max(prof.weight, 1e-9)
        if prof.limit > 0:
            self._l_tags[best] = max(self._l_tags[best], now) + \
                1.0 / prof.limit
        _seq, enq_ts, item = self._queues[best].pop(0)
        wait = max(0.0, now - enq_ts)
        st = self.stats[best]
        st["dequeued"] += 1
        st[f"{phase}_served"] += 1
        st["wait_sum"] += wait
        st["wait_max"] = max(st["wait_max"], wait)
        self.last_phase = phase
        if self.perf is not None:
            self.perf.dinc(f"mclock_dequeued_{best}")
            self.perf.dinc(f"mclock_{phase}_served_{best}")
            self.perf.hinc(f"lat_qwait_{best}", wait)
        return item

    # -- introspection -------------------------------------------------------

    def dump(self) -> dict:
        """Per-class QoS state for the `dump_mclock` asok command:
        profile triples, queue depths, phase serve counts, waits."""
        return {
            "vtime": self._vtime,
            "classes": {
                c: {
                    "profile": {
                        "reservation": self.profiles[c].reservation,
                        "weight": self.profiles[c].weight,
                        "limit": self.profiles[c].limit,
                    },
                    "queue_len": len(self._queues[c]),
                    **self.stats[c],
                }
                for c in self._queues},
        }

    def empty(self) -> bool:
        return all(not q for q in self._queues.values())

    def __len__(self):
        return sum(len(q) for q in self._queues.values())


def make_scheduler(kind: str, conf=None, perf=None):
    """reference OpScheduler.cc:24 make_scheduler."""
    if kind == "mclock":
        profiles = profiles_from_conf(conf) if conf is not None else None
        return MClockScheduler(profiles, perf=perf)
    return WeightedPriorityQueue()


class ShardedOpWQ:
    """N worker threads draining a scheduler (reference OSD.h:1568
    ShardedOpWQ: the thread pool between dispatch and PG work).  Items
    are thunks; op classes map to scheduler classes."""

    def __init__(self, n_threads: int = 2, kind: str = "wpq",
                 conf=None, perf=None):
        self.scheduler = make_scheduler(kind, conf=conf, perf=perf)
        self._cv = threading.Condition()
        self._stop = False
        self._abort = False
        self.threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"osd-op-wq-{i}")
            for i in range(n_threads)]
        for t in self.threads:
            t.start()

    def apply_conf(self, conf) -> None:
        """Re-resolve mclock profiles after a runtime config change
        (the OSD's osd_mclock_* observers land here)."""
        with self._cv:
            if isinstance(self.scheduler, MClockScheduler):
                self.scheduler.apply_conf(conf)

    def wire_class_ok(self, op_class: str) -> bool:
        """True when a client-declared QoS class may be honored: it
        must be operator-provisioned (a profile triple exists — the
        OSD collapses UNDECLARED wire strings into "client", since
        per-class queues/tags/counters live for the daemon's lifetime
        and arbitrary strings would mint unbounded scheduler state
        and metric cardinality) and must not name an internal
        background class (WIRE_BLOCKED_CLASSES)."""
        if op_class in WIRE_BLOCKED_CLASSES:
            return False
        with self._cv:
            return isinstance(self.scheduler, MClockScheduler) and \
                op_class in self.scheduler.profiles

    def dump(self) -> dict:
        with self._cv:
            if isinstance(self.scheduler, MClockScheduler):
                return self.scheduler.dump()
            return {"kind": "wpq", "queue_len": len(self.scheduler)}

    def queue(self, fn: Callable[[], None], op_class: str = "client",
              priority: int = 63, top=None) -> None:
        """top: optional TrackedOp (common/tracked_op.py) — the
        scheduler marks `queued` / `dequeued` on its timeline so queue
        wait is attributable per op (reference OpTracker events around
        the OSD op queue)."""
        if top is not None and getattr(top, "is_tracked", False):
            top.mark_event("queued")
            inner = fn

            def fn():
                top.mark_event("dequeued")
                inner()
        with self._cv:
            if isinstance(self.scheduler, MClockScheduler):
                self.scheduler.enqueue(fn, op_class=op_class)
            else:
                self.scheduler.enqueue(
                    fn, priority=priority,
                    strict=(op_class == "client" and priority >= 196))
            self._cv.notify()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while self.scheduler.empty() and not self._stop:
                    self._cv.wait(0.5)
                # stop once the backlog is drained (queued ops were
                # accepted — dropping them would strand their clients
                # until the op timeout), or IMMEDIATELY on abort (the
                # drain grace expired: the daemon is tearing down its
                # messenger/store, and ops applied past that point
                # could race a revived daemon on the same store)
                if self._abort or (self._stop and
                                   self.scheduler.empty()):
                    return
                fn = self.scheduler.dequeue()
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    import traceback
                    traceback.print_exc()

    def drain_and_stop(self, grace: float = 2.0) -> None:
        """Workers drain the accepted backlog for up to `grace`
        seconds, then abort — a bounded teardown window, unlike the
        executor's shutdown(wait=False) which keeps running every
        already-queued task unboundedly."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        deadline = time.monotonic() + grace
        for t in self.threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        with self._cv:
            self._abort = True
            self._cv.notify_all()
        for t in self.threads:
            t.join(timeout=1)
