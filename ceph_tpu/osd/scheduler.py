"""Op scheduler: QoS between client / recovery / scrub work.

Re-expresses reference src/osd/scheduler/ (OpScheduler.cc:24
make_scheduler): a pluggable queue the OSD's worker shards pull from,
either weighted-priority (WPQ) or an mClock-style
reservation/weight/limit dequeuer (src/osd/scheduler/mClockScheduler.h,
src/dmclock submodule).  The mClock here implements the core dmclock
idea — per-class virtual tags from (reservation, weight, limit) — not
the full distributed protocol.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass(order=True)
class _WPQItem:
    sort_key: tuple
    item: Any = field(compare=False)


class WeightedPriorityQueue:
    """Strict-then-weighted priorities (reference WeightedPriorityQueue):
    strict items first; others dequeued proportionally to priority."""

    def __init__(self):
        self._strict: list = []
        self._heap: list[_WPQItem] = []
        self._counter = itertools.count()
        self._vclock = 0.0

    def enqueue(self, item, priority: int = 63, strict: bool = False):
        if strict:
            self._strict.append((priority, next(self._counter), item))
            self._strict.sort(key=lambda t: (-t[0], t[1]))
        else:
            # virtual finish time ~ 1/priority spacing
            self._vclock += 1.0
            key = (self._vclock / max(priority, 1), next(self._counter))
            heapq.heappush(self._heap, _WPQItem(key, item))

    def dequeue(self):
        if self._strict:
            return self._strict.pop(0)[2]
        if self._heap:
            return heapq.heappop(self._heap).item
        return None

    def empty(self) -> bool:
        return not self._strict and not self._heap

    def __len__(self):
        return len(self._strict) + len(self._heap)


@dataclass
class ClientProfile:
    """dmclock (reservation, weight, limit) triple per op class."""
    reservation: float = 0.0   # ops/sec guaranteed
    weight: float = 1.0        # proportional share
    limit: float = 0.0         # ops/sec cap (0 = none)


class MClockScheduler:
    """Single-node dmclock: tag ops with reservation/proportional virtual
    times, serve reservation-eligible first, then by weight, respecting
    limits (reference mClockScheduler defaults: client/recovery/scrub
    classes)."""

    DEFAULT_PROFILES = {
        "client": ClientProfile(reservation=100.0, weight=2.0),
        "recovery": ClientProfile(reservation=10.0, weight=1.0,
                                  limit=500.0),
        "scrub": ClientProfile(reservation=5.0, weight=0.5, limit=200.0),
    }

    def __init__(self, profiles: dict[str, ClientProfile] | None = None):
        self.profiles = dict(profiles or self.DEFAULT_PROFILES)
        self._queues: dict[str, list] = {c: [] for c in self.profiles}
        self._r_tags: dict[str, float] = {c: 0.0 for c in self.profiles}
        self._p_tags: dict[str, float] = {c: 0.0 for c in self.profiles}
        self._counter = itertools.count()

    def enqueue(self, item, op_class: str = "client", **_):
        if op_class not in self._queues:
            self._queues[op_class] = []
            self.profiles[op_class] = ClientProfile()
            self._r_tags[op_class] = 0.0
            self._p_tags[op_class] = 0.0
        self._queues[op_class].append((next(self._counter), item))

    def dequeue(self):
        now = time.monotonic()
        # 1: reservation phase — any class behind its reservation tag
        best = None
        for c, q in self._queues.items():
            if not q:
                continue
            prof = self.profiles[c]
            if prof.reservation > 0 and self._r_tags[c] <= now:
                if best is None or self._r_tags[c] < self._r_tags[best]:
                    best = c
        if best is None:
            # 2: proportional phase by weight tags (limit-respecting)
            for c, q in self._queues.items():
                if not q:
                    continue
                prof = self.profiles[c]
                if prof.limit > 0 and self._p_tags[c] > now:
                    continue
                if best is None or \
                        self._p_tags[c] / max(self.profiles[c].weight, 1e-9) < \
                        self._p_tags[best] / max(self.profiles[best].weight,
                                                 1e-9):
                    best = c
        if best is None:
            # 3: work-conserving fallback — nothing reservation-eligible
            # and every limited class is ahead of its cap; serve the
            # lowest weighted tag anyway (limits only bind under
            # contention, as in dmclock)
            for c, q in self._queues.items():
                if not q:
                    continue
                if best is None or \
                        self._p_tags[c] / max(self.profiles[c].weight, 1e-9) < \
                        self._p_tags[best] / max(self.profiles[best].weight,
                                                 1e-9):
                    best = c
        if best is None:
            return None
        prof = self.profiles[best]
        if prof.reservation > 0:
            self._r_tags[best] = max(self._r_tags[best], now) + \
                1.0 / prof.reservation
        rate = prof.limit if prof.limit > 0 else 1000.0
        self._p_tags[best] = max(self._p_tags[best], now) + 1.0 / rate
        return self._queues[best].pop(0)[1]

    def empty(self) -> bool:
        return all(not q for q in self._queues.values())

    def __len__(self):
        return sum(len(q) for q in self._queues.values())


def make_scheduler(kind: str):
    """reference OpScheduler.cc:24 make_scheduler."""
    if kind == "mclock":
        return MClockScheduler()
    return WeightedPriorityQueue()


class ShardedOpWQ:
    """N worker threads draining a scheduler (reference OSD.h:1568
    ShardedOpWQ: the thread pool between dispatch and PG work).  Items
    are thunks; op classes map to scheduler classes."""

    def __init__(self, n_threads: int = 2, kind: str = "wpq"):
        self.scheduler = make_scheduler(kind)
        self._cv = threading.Condition()
        self._stop = False
        self.threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"osd-op-wq-{i}")
            for i in range(n_threads)]
        for t in self.threads:
            t.start()

    def queue(self, fn: Callable[[], None], op_class: str = "client",
              priority: int = 63, top=None) -> None:
        """top: optional TrackedOp (common/tracked_op.py) — the
        scheduler marks `queued` / `dequeued` on its timeline so queue
        wait is attributable per op (reference OpTracker events around
        the OSD op queue)."""
        if top is not None and getattr(top, "is_tracked", False):
            top.mark_event("queued")
            inner = fn

            def fn():
                top.mark_event("dequeued")
                inner()
        with self._cv:
            if isinstance(self.scheduler, MClockScheduler):
                self.scheduler.enqueue(fn, op_class=op_class)
            else:
                self.scheduler.enqueue(
                    fn, priority=priority,
                    strict=(op_class == "client" and priority >= 196))
            self._cv.notify()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while self.scheduler.empty() and not self._stop:
                    self._cv.wait(0.5)
                if self._stop:
                    return
                fn = self.scheduler.dequeue()
            if fn is not None:
                try:
                    fn()
                except Exception:  # noqa: BLE001
                    import traceback
                    traceback.print_exc()

    def drain_and_stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self.threads:
            t.join(timeout=2)
