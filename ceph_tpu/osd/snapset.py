"""SnapSet: per-object snapshot/clone bookkeeping.

Re-expresses the reference's SnapSet machinery (src/osd/osd_types.h
SnapSet, PrimaryLogPG::make_writeable, src/osd/PrimaryLogPG.cc) at the
fidelity self-managed snapshots need:

- Clients carry a SnapContext (seq + existing snap ids) on writes.
- The head object's SnapSet xattr records the newest seq it has seen
  and its clone list.  A write whose snapc.seq is newer than the
  recorded seq first CLONES the head to an object whose hobject.snap
  is the snapc seq (copy-on-write), then applies.
- A read at snap s resolves to the OLDEST clone with clone_snap >= s
  (that clone holds the content as of s); with no such clone the head
  serves (the object hasn't changed since s) — unless the object was
  born after s.
"""

from __future__ import annotations

import json

SS_KEY = "snapset"
# When a head is deleted under a SnapContext its SnapSet moves to a
# snapdir object (hobject.snap = SNAPDIR) so the clone history survives
# a later recreate (reference CEPH_SNAPDIR).
SNAPDIR = 1 << 62


class SnapSet:
    def __init__(self, seq: int = 0, clones: list[int] | None = None,
                 born: int = 0, prior_born: int = 0):
        self.seq = seq             # newest snap id this head has seen
        self.clones = clones or []  # clone snap ids, ascending
        self.born = born           # snap seq when the head was created
        # birth seq of the PREVIOUS incarnation (delete+recreate):
        # prior-incarnation clones never serve snaps older than it
        self.prior_born = prior_born

    def encode(self) -> bytes:
        return json.dumps({"seq": self.seq, "clones": self.clones,
                           "born": self.born,
                           "pborn": self.prior_born}).encode()

    @classmethod
    def decode(cls, raw: bytes | None) -> "SnapSet":
        if not raw:
            return cls()
        j = json.loads(raw.decode())
        return cls(j.get("seq", 0), list(j.get("clones", [])),
                   j.get("born", 0), j.get("pborn", 0))

    def needs_cow(self, snapc_seq: int) -> bool:
        return snapc_seq > self.seq

    def add_clone(self, snap_id: int) -> None:
        self.clones.append(snap_id)
        self.clones.sort()
        self.seq = max(self.seq, snap_id)

    def resolve(self, snap: int) -> int | None:
        """Which object serves a read at snap id `snap`?
        Returns the clone snap id, 0 for the head, or None when the
        object did not exist at that snap.

        A clone older than `born` belongs to a previous incarnation
        (the head was deleted and recreated; the clone history rode the
        snapdir): it still serves its snaps.  A clone newer than `born`
        only covers snaps after the (re)creation."""
        c = next((cs for cs in self.clones if cs >= snap), None)
        if c is not None:
            if c <= self.born:
                # prior-incarnation clone: still fenced by ITS birth
                return c if snap > self.prior_born else None
            return c if snap > self.born else None
        return 0 if snap > self.born else None
