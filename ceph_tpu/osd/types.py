"""Core object/placement types.

Re-expresses the reference's osd_types (src/osd/osd_types.h): object and
placement-group identities, shard ids, eversion ordering, and the pool
type constants the backends switch on.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from enum import IntEnum

NO_SHARD = -1
NO_GEN = 0xFFFFFFFFFFFFFFFF


class PoolType(IntEnum):
    """pg_pool_t types (reference osd_types.h TYPE_REPLICATED/TYPE_ERASURE)."""
    REPLICATED = 1
    ERASURE = 3


@dataclass(frozen=True, order=True)
class hobject_t:
    """Hashed object id (reference src/common/hobject.h): name + key +
    snapshot + a placement hash that decides its PG."""
    pool: int = 0
    name: str = ""
    key: str = ""
    snap: int = 0
    hash: int = 0

    def with_hash(self, h: int) -> "hobject_t":
        return replace(self, hash=h & 0xFFFFFFFF)


@dataclass(frozen=True, order=True)
class ghobject_t:
    """Generational + sharded object id (reference hobject.h ghobject_t):
    what actually keys the ObjectStore.  EC keeps old generations for
    rollback (reference ecbackend.rst; generation bumped on overwrite)."""
    hobj: hobject_t = field(default_factory=hobject_t)
    generation: int = NO_GEN
    shard: int = NO_SHARD

    def no_gen(self) -> "ghobject_t":
        return replace(self, generation=NO_GEN)


@dataclass(frozen=True, order=True)
class pg_t:
    """Placement group id: pool + seed (reference osd_types.h pg_t)."""
    pool: int = 0
    seed: int = 0

    def __str__(self) -> str:
        return f"{self.pool}.{self.seed:x}"


@dataclass(frozen=True, order=True)
class spg_t:
    """Shard-addressed PG (reference osd_types.h spg_t): which shard of
    an EC PG a message/store-collection refers to."""
    pgid: pg_t = field(default_factory=pg_t)
    shard: int = NO_SHARD

    def __str__(self) -> str:
        return f"{self.pgid}s{self.shard}" if self.shard != NO_SHARD \
            else str(self.pgid)


@dataclass(frozen=True, order=True)
class eversion_t:
    """Epoch+version log position (reference osd_types.h eversion_t)."""
    epoch: int = 0
    version: int = 0

    def __str__(self) -> str:
        return f"{self.epoch}'{self.version}"
