"""The OSD daemon: dispatch, PG backends, shard fan-out, heartbeats.

Re-expresses the reference OSD's runtime shape (src/osd/OSD.{h,cc},
src/ceph_osd.cc): boot = bind messenger + announce to mon + subscribe to
maps (OSD::init, reference OSD.cc:3257); client ops fast-dispatch into
per-PG backends (ms_fast_dispatch -> enqueue_op -> do_request, reference
OSD.cc:6990/9577); EC sub-ops apply shard transactions and ack
(ECBackend::handle_sub_write, reference ECBackend.cc:915); heartbeats
ping peers and report failures to the mon (handle_osd_ping, reference
OSD.cc:5210 + failure_queue :5502).

Idiomatic shifts: the ShardedOpWQ thread-shards collapse into the
messenger's dispatcher pool (Python threads are not the scaling axis
here — the TPU codec launch is, and it batches inside ECBackend); the
PG/PeeringState machinery runs full log-based peering on map change
(_peer_pg below: GetLog-style shard interrogation, authoritative-log
selection by min last_update with last_epoch_started fencing, divergent
rollback, stale-shard adoption — the role of the reference's
boost::statechart in src/osd/PeeringState.h, expressed as one
deterministic pass instead of an event machine).
"""

from __future__ import annotations

import errno
import threading
import time

import numpy as np

from ..common.tracked_op import NULL_TRACKED, OpTracker, TraceContext
from ..crush.hash import crush_hash32
from ..ec import ErasureCodeError, ErasureCodePluginRegistry, Profile
from ..msg import Messenger
from ..msg import messages as M
from ..osd.osd_map import OSDMap, apply_inc_chain
from ..store import MemStore
from ..store.object_store import ObjectStore, Transaction
from .ec_backend import ECBackend, ShardBackend
from .ec_transaction import PGTransaction, shard_oid
from .ec_util import HINFO_KEY, HashInfo, StripeInfo
from .replicated_backend import ReplicaBackend, ReplicatedBackend
from .types import NO_SHARD, eversion_t, ghobject_t, hobject_t, pg_t, spg_t


class MessengerShardBackend(ShardBackend):
    """ShardBackend over the wire: sub-ops to the acting set's OSDs,
    local shard applied directly (reference try_reads_to_commit's split
    between messenger sends :2074 and local handle_sub_write :2086)."""

    RPC_TIMEOUT = 20.0

    def __init__(self, daemon: "OSDDaemon", pgid: pg_t, acting: list[int]):
        self.daemon = daemon
        self.pgid = pgid
        self.acting = list(acting)
        self.lock = threading.Lock()
        self._tid = 0
        self._pending_writes: dict[int, tuple] = {}
        self._pending_reads: dict[int, tuple] = {}
        self.degraded_shards: set[int] = set()

    def _next_tid(self) -> int:
        with self.lock:
            self._tid += 1
            return self._tid

    def _osd_for(self, shard: int) -> int | None:
        """Acting OSD for a shard; None for holes / down OSDs."""
        from ..crush.map import CRUSH_ITEM_NONE
        osd = self.acting[shard]
        if osd == CRUSH_ITEM_NONE or not self.daemon.osdmap.is_up(osd):
            return None
        return osd

    # -- writes -------------------------------------------------------------

    def sub_write(self, shard, txn, on_commit, log_entries=None,
                  at_version=None, rollforward_to=None, trace=None,
                  top=None):
        from .pg_log import entry_to_wire
        osd = self._osd_for(shard)
        spg = spg_t(self.pgid, shard)
        if osd is None:
            # Hole in the acting set: the shard is degraded; ack now and
            # leave the rebuild to recovery.  Safe only because op
            # admission already enforced pool min_size (live shards >=
            # min_size), mirroring the reference's split between
            # PeeringState min_size gating and degraded-write tolerance.
            self.degraded_shards.add(shard)
            on_commit(shard)
            return
        wire_entries = [entry_to_wire(e) for e in (log_entries or [])]
        if osd == self.daemon.osd_id:
            self.daemon.apply_sub_write(spg, txn, wire_entries,
                                        at_version or eversion_t(),
                                        rollforward_to)
            on_commit(shard)
            return
        tid = self._next_tid()
        with self.lock:
            self._pending_writes[tid] = (on_commit, shard)
        conn = self.daemon.conn_to_osd(osd)
        m = M.MOSDECSubOpWrite(
            spg, tid, at_version or eversion_t(), txn,
            log_entries=wire_entries, rollforward_to=rollforward_to,
            trace=trace)
        if top is not None:
            # wire-plane trace stitch: the msgr ledger stamps
            # msgr_send(peer) on the tracked op once the frame is
            # actually written, so send-queue time is attributable
            m._top = top
        conn.send_message(m)

    def handle_write_reply(self, msg: M.MOSDECSubOpWriteReply) -> None:
        with self.lock:
            ent = self._pending_writes.pop(msg.tid, None)
        if ent:
            on_commit, shard = ent
            # Replies are fast-dispatched on the reactor, but
            # on_commit transitively runs the write pipeline
            # (try_finish_rmw -> check_ops -> possibly a BLOCKING
            # probe() whose stat replies must be delivered by this
            # very loop) — always punt to the dispatch executor.
            Messenger.submit_dispatch(on_commit, shard)

    # -- reads --------------------------------------------------------------

    def sub_read(self, shard, oid, off, length, on_done):
        osd = self._osd_for(shard)
        spg = spg_t(self.pgid, shard)
        if osd is None:
            on_done(shard, None)
            return
        if osd == self.daemon.osd_id:
            data = self.daemon.read_shard(spg, oid, off, length)
            on_done(shard, data)
            return
        tid = self._next_tid()
        with self.lock:
            self._pending_reads[tid] = (on_done, shard)
        conn = self.daemon.conn_to_osd(osd)
        conn.send_message(M.MOSDECSubOpRead(spg, tid, oid, off, length))

    def sub_read_batch(self, reqs, on_done) -> None:
        """Fan out [(shard, oid, off, length), ...] with ONE reactor
        task for all remote sends; the local shard (if any) is read
        after the remote requests are in flight."""
        pairs = []
        local = []
        for shard, oid, off, length in reqs:
            osd = self._osd_for(shard)
            spg = spg_t(self.pgid, shard)
            if osd is None:
                on_done(shard, None)
                continue
            if osd == self.daemon.osd_id:
                local.append((spg, shard, oid, off, length))
                continue
            tid = self._next_tid()
            with self.lock:
                self._pending_reads[tid] = (on_done, shard)
            conn = self.daemon.conn_to_osd(osd)
            pairs.append((conn, M.MOSDECSubOpRead(spg, tid, oid, off,
                                                  length)))
        if pairs:
            self.daemon.messenger.send_batch(pairs)
        for spg, shard, oid, off, length in local:
            on_done(shard, self.daemon.read_shard(spg, oid, off, length))

    def handle_read_reply(self, msg: M.MOSDECSubOpReadReply) -> None:
        with self.lock:
            ent = self._pending_reads.pop(msg.tid, None)
        if ent:
            on_done, shard = ent
            data = (np.frombuffer(msg.data, dtype=np.uint8)
                    if msg.result == 0 else None)
            if getattr(on_done, "loop_safe", False):
                # gather callbacks (store + Event.set) may run inline
                # on the reactor — the hot client-read fan-out path
                on_done(shard, data)
            else:
                # RMW pre-reads continue the write pipeline (decode +
                # encode + possibly blocking probe()): off the loop
                Messenger.submit_dispatch(on_done, shard, data)

    # -- sync metadata RPCs -------------------------------------------------

    def _stat_rpc(self, shard: int, oid: hobject_t, want_attrs: bool
                  ) -> M.MOSDECSubOpReadReply | None:
        osd = self._osd_for(shard)
        spg = spg_t(self.pgid, shard)
        if osd is None:
            return None
        if osd == self.daemon.osd_id:
            return self.daemon.stat_shard(spg, oid, want_attrs)
        tid = self._next_tid()
        box: dict = {}
        ev = threading.Event()

        def on_done_raw(msg):
            box["msg"] = msg
            ev.set()

        with self.lock:
            self._pending_reads[tid] = (None, shard)
            self.daemon.raw_read_waiters[(spg, tid)] = on_done_raw
        conn = self.daemon.conn_to_osd(osd)
        conn.send_message(
            M.MOSDECSubOpRead(spg, tid, oid, 0, 0, want_attrs=want_attrs))
        ev.wait(self.RPC_TIMEOUT)
        with self.lock:
            self._pending_reads.pop(tid, None)
        return box.get("msg")

    def get_hinfo(self, shard, oid):
        reply = self._stat_rpc(shard, oid, want_attrs=True)
        if reply is None or reply.result != 0:
            return None
        raw = reply.attrs.get(HINFO_KEY)
        return HashInfo.decode(raw) if raw else None

    def probe(self, oid, n):
        """(hinfo, shard size) in ONE metadata round: the local shard
        answers without touching the wire (hinfo rides every shard, so
        steady-state writes cost ZERO metadata RPCs), and only a miss
        fans out to the remaining shards CONCURRENTLY — one RTT where
        the sequential sweep cost n (the dominant per-op latency in
        the end-to-end write path)."""
        hinfo = None
        size = None
        remote = []
        for s in range(n):
            osd = self._osd_for(s)
            if osd is None:
                continue
            if osd == self.daemon.osd_id:
                reply = self.daemon.stat_shard(spg_t(self.pgid, s),
                                               oid, True)
                if reply.result == 0:
                    raw = reply.attrs.get(HINFO_KEY)
                    if raw:
                        hinfo = HashInfo.decode(raw)
                    if reply.size >= 0:
                        size = reply.size
            else:
                remote.append((s, osd))
        if hinfo is not None or not remote:
            return hinfo, size
        box: dict = {}
        ev = threading.Event()
        pending = {"n": len(remote)}
        issued: list[tuple] = []
        for s, osd in remote:
            spg = spg_t(self.pgid, s)
            tid = self._next_tid()

            def mk(s=s):
                def cb(msg):
                    with self.lock:   # box is read under this lock
                        box[s] = msg
                        pending["n"] -= 1
                        fire = pending["n"] <= 0
                    if fire:
                        ev.set()
                return cb

            with self.lock:
                self.daemon.raw_read_waiters[(spg, tid)] = mk()
            issued.append((spg, tid))
            try:
                self.daemon.conn_to_osd(osd).send_message(
                    M.MOSDECSubOpRead(spg, tid, oid, 0, 0,
                                      want_attrs=True))
            except Exception:  # noqa: BLE001 - unreachable peer
                with self.lock:
                    pending["n"] -= 1
                    fire = pending["n"] <= 0
                if fire:
                    ev.set()
        ev.wait(self.RPC_TIMEOUT)
        with self.lock:
            for spg, tid in issued:
                self.daemon.raw_read_waiters.pop((spg, tid), None)
            replies = dict(box)   # late callbacks mutate box concurrently
        for s in sorted(replies):
            msg = replies[s]
            if msg.result != 0:
                continue
            if hinfo is None:
                raw = msg.attrs.get(HINFO_KEY)
                if raw:
                    hinfo = HashInfo.decode(raw)
            if size is None and msg.size >= 0:
                size = msg.size
        return hinfo, size

    def get_attrs(self, shard, oid):
        reply = self._stat_rpc(shard, oid, want_attrs=True)
        if reply is None or reply.result != 0:
            return None
        return dict(reply.attrs)

    def stat(self, shard, oid):
        reply = self._stat_rpc(shard, oid, want_attrs=False)
        if reply is None or reply.result != 0 or reply.size < 0:
            return None
        return reply.size


class MessengerReplicaBackend(ReplicaBackend):
    """ReplicaBackend over the wire: replica 0 local, others remote."""

    def __init__(self, daemon: "OSDDaemon", pgid: pg_t, acting: list[int]):
        self.daemon = daemon
        self.pgid = pgid
        self.acting = list(acting)
        self.n_replicas = len(acting)
        self.lock = threading.Lock()
        self._tid = 0
        self._pending: dict[int, tuple] = {}

    def rep_write(self, replica, txn, on_commit):
        from ..crush.map import CRUSH_ITEM_NONE
        osd = self.acting[replica]
        spg = spg_t(self.pgid, NO_SHARD)
        if osd == CRUSH_ITEM_NONE or not self.daemon.osdmap.is_up(osd):
            # down/unplaced replica: not a write target this interval
            # (recovery re-syncs it on return; min_size gating already
            # guaranteed enough live copies before we got here)
            on_commit(replica)
            return
        if osd == self.daemon.osd_id:
            self.daemon.apply_shard_txn(spg, txn)
            on_commit(replica)
            return
        with self.lock:
            self._tid += 1
            tid = self._tid
            self._pending[tid] = (on_commit, replica)
        self.daemon.conn_to_osd(osd).send_message(
            M.MOSDECSubOpWrite(spg, tid, eversion_t(), txn))

    def handle_write_reply(self, msg) -> None:
        with self.lock:
            ent = self._pending.pop(msg.tid, None)
        if ent:
            on_commit, replica = ent
            on_commit(replica)

    def local_read(self, oid, off, length):
        data = self.daemon.read_shard(
            spg_t(self.pgid, NO_SHARD), oid, off,
            length if length is not None else -1)
        import numpy as np
        return data if data is not None else np.empty(0, dtype=np.uint8)

    def local_stat(self, oid):
        reply = self.daemon.stat_shard(spg_t(self.pgid, NO_SHARD),
                                       oid, False)
        return reply.size if reply.result == 0 and reply.size >= 0 else None


class PGState:
    """Per-PG primary-side state: backend + version counter."""

    def __init__(self, backend, kind: str):
        self.backend = backend
        self.kind = kind  # "ec" | "replicated"
        self.version = 0
        self.lock = threading.RLock()   # held across alloc+submit
        # peering: a fresh primary must collect shard logs before
        # serving (reference PeeringState: no ops until Active)
        self.needs_peer = True
        self.peer_lock = threading.Lock()
        # head SnapSet seq cache: steady-state writes under an
        # unchanged SnapContext skip the attrs fetch (only this
        # primary mutates heads, so the cache is authoritative)
        self.snap_seqs: dict = {}

    def next_version(self, epoch: int) -> eversion_t:
        with self.lock:
            self.version += 1
            return eversion_t(epoch, self.version)


class OSDDaemon:
    def __init__(self, osd_id: int, mon_addr,
                 store: ObjectStore | None = None,
                 addr: tuple[str, int] = ("127.0.0.1", 0),
                 heartbeat_interval: float = 0.0,
                 asok_path: str | None = None,
                 auth=None, secure: bool = False,
                 conf: dict | None = None):
        from ..common.context import CephContext
        from ..common.perf_counters import PerfCountersBuilder
        self.osd_id = osd_id
        self.cct = CephContext(f"osd.{osd_id}", asok_path)
        # startup conf overrides must land BEFORE anything reads them:
        # options like osd_op_queue choose construction-time shape
        # (the scheduler kind), so post-construction .set() is too late
        for _k, _v in (conf or {}).items():
            self.cct.conf.set(_k, _v)
        self.cct.preload_erasure_code()
        self.perf = self.cct.perf.add(
            PerfCountersBuilder(f"osd.{osd_id}")
            .add_u64_counter("op", "client ops received")
            .add_u64_counter("op_w", "mutating ops")
            .add_u64_counter("op_r", "read ops")
            .add_u64_counter("subop_w", "shard sub-writes applied")
            .add_u64_counter("subop_r", "shard sub-reads served")
            .add_time_avg("op_latency", "client op latency")
            .add_u64_counter("recovery_queued_ops",
                             "rebuild units routed through the "
                             "scheduler's recovery class")
            .add_u64_counter("recovery_pushed_bytes",
                             "rebuilt shard bytes pushed to acting "
                             "homes")
            .add_time_avg("recovery_throttle_wait",
                          "time recovery pushes spent waiting on the "
                          "bandwidth throttle")
            .add_gauge("pg_degraded", "led PGs with recovery pending")
            .add_gauge("pg_misplaced",
                       "objects with split/merge pushes pending")
            .add_gauge("pg_unfound", "objects latched unfound")
            # heartbeat tick-lag detector (the compile-stall flap
            # evidence PR 8's note asked for): how late the last
            # heartbeat tick ran vs osd_heartbeat_interval
            .add_gauge("hb_tick_lag",
                       "seconds the last heartbeat tick ran past "
                       "its osd_heartbeat_interval schedule")
            .add_u64_counter("hb_tick_lag_events",
                             "heartbeat ticks delayed a full extra "
                             "interval or more past schedule (logged)")
            .create_perf_counters())
        # request tracing (reference TrackedOp/OpTracker, docs/
        # TRACING.md): always-on per-op event timelines + per-stage
        # latency histograms; conf observers keep the master switch
        # and complaint time live-tunable (injectargs / pre-boot conf)
        _tconf = self.cct.conf
        self.op_tracker = OpTracker(
            enabled=bool(_tconf.get("osd_enable_op_tracker")),
            complaint_time=float(_tconf.get("osd_op_complaint_time")),
            history_size=int(_tconf.get("osd_op_history_size")),
            history_slow_size=int(
                _tconf.get("osd_op_history_slow_size")),
            perf=self.cct.perf.add(
                PerfCountersBuilder(f"optracker.osd.{osd_id}")
                .create_perf_counters()))

        def _apply_track(_k=None, _v=None):
            self.op_tracker.enabled = bool(
                _tconf.get("osd_enable_op_tracker"))
            self.op_tracker.complaint_time = float(
                _tconf.get("osd_op_complaint_time"))
        for _opt in ("osd_enable_op_tracker", "osd_op_complaint_time"):
            _tconf.add_observer(_opt, _apply_track)
        # device-plane flight recorder (ops/profiler.py, docs/
        # TRACING.md "Device plane"): the HOST singleton — its perf
        # set (lat_launch_* histograms, ec_compile_stalls) registers
        # into exactly ONE daemon's collection per host (the launch-
        # queue rule: re-exporting a shared singleton from every
        # daemon would make sum-across-daemons read n_daemons x the
        # truth), and the same daemon ships the windowed compile
        # report monward for COMPILE_STORM
        from ..ops.profiler import DeviceProfiler
        self._profiler = DeviceProfiler.host_instance()
        self._profiler_reporter = False
        if not getattr(self._profiler, "_perf_registered", False):
            self._profiler._perf_registered = True
            self._profiler_reporter = True
            self.cct.perf.add(self._profiler.perf)
            self._profiler.set_ring_size(
                int(_tconf.get("osd_ec_profiler_ring")))
        # persistent XLA compile cache (ops/compile_cache.py, docs/
        # PIPELINE.md "Compile lifecycle"): point jax at the on-disk
        # cache BEFORE any jit compile this daemon triggers — a
        # restarted daemon re-traces but never re-compiles.  One
        # directory per host (first enabler wins, like the profiler
        # perf owner); failures leave the cache off, never fail boot
        self._prewarm_status: dict | None = None
        if bool(_tconf.get("osd_ec_compile_cache")):
            from ..ops import compile_cache
            compile_cache.enable(
                str(_tconf.get("osd_ec_compile_cache_dir") or "")
                or None)

        def _apply_prof(_k=None, _v=None):
            p = self._profiler
            p.enabled = bool(_tconf.get("osd_ec_profiler"))
            p.stall_s = float(_tconf.get("osd_ec_compile_stall_s"))
            p.storm_window_s = float(
                _tconf.get("osd_ec_compile_storm_window_s"))
            p.inject_stall_s = float(
                _tconf.get("osd_ec_inject_compile_stall") or 0.0)
        _apply_prof()
        for _opt in ("osd_ec_profiler", "osd_ec_compile_stall_s",
                     "osd_ec_compile_storm_window_s",
                     "osd_ec_inject_compile_stall"):
            _tconf.add_observer(_opt, _apply_prof)
        # control-plane flight recorder (osd/pg_ledger.py, docs/
        # TRACING.md "Control plane"): per-DAEMON, not a host
        # singleton — peering/recovery is this daemon's own work, so
        # every daemon registers its own perf set and ships its own
        # MPGStats ledger block (no profiler-style perf-owner rule)
        from .pg_ledger import PGLedger
        self.pg_ledger = PGLedger(
            name=f"pg_ledger.osd.{osd_id}",
            ring=int(_tconf.get("osd_pg_ledger_ring")))
        self.cct.perf.add(self.pg_ledger.perf)

        def _apply_ledger(_k=None, _v=None):
            self.pg_ledger.enabled = bool(
                _tconf.get("osd_pg_ledger"))
        _apply_ledger()
        _tconf.add_observer("osd_pg_ledger", _apply_ledger)
        if self.cct.asok is not None:
            self.cct.asok.register_command(
                "status", lambda cmd: {
                    "osd": self.osd_id,
                    "epoch": self.osdmap.epoch,
                    "num_pgs": len(self.pgs)})
            self.cct.asok.register_command("scrub", self._asok_scrub)
            self.cct.asok.register_command(
                "dump_ops_in_flight", self._asok_dump_ops_in_flight)
            self.cct.asok.register_command(
                "dump_historic_ops",
                lambda cmd: self.op_tracker.dump_historic_ops())
            self.cct.asok.register_command(
                "dump_historic_slow_ops",
                lambda cmd: self.op_tracker.dump_historic_slow_ops())
            # multichip plane state (docs/MULTICHIP.md); both
            # spellings: `ceph daemon ASOK mesh status` and the
            # one-word form
            self.cct.asok.register_command(
                "mesh status", self._asok_mesh_status)
            self.cct.asok.register_command(
                "mesh_status", self._asok_mesh_status)
            # per-host EC launch queue occupancy (cross-PG continuous
            # batching, docs/PIPELINE.md); both spellings like mesh
            self.cct.asok.register_command(
                "launch queue status", self._asok_launch_queue_status)
            self.cct.asok.register_command(
                "launch_queue_status", self._asok_launch_queue_status)
            # repair subsystem state (docs/REPAIR.md); both spellings
            # like mesh/launch-queue
            self.cct.asok.register_command(
                "repair status", self._asok_repair_status)
            self.cct.asok.register_command(
                "repair_status", self._asok_repair_status)
            # device-plane flight recorder (docs/TRACING.md "Device
            # plane"); both spellings like mesh/launch-queue
            self.cct.asok.register_command(
                "launch profile", self._asok_launch_profile)
            self.cct.asok.register_command(
                "launch_profile", self._asok_launch_profile)
            self.cct.asok.register_command(
                "compile ledger", self._asok_compile_ledger)
            self.cct.asok.register_command(
                "compile_ledger", self._asok_compile_ledger)
            # boot-time prewarm state (ops/prewarm.py); both
            # spellings like mesh/launch-queue
            self.cct.asok.register_command(
                "prewarm status", self._asok_prewarm_status)
            self.cct.asok.register_command(
                "prewarm_status", self._asok_prewarm_status)
            # control-plane flight recorder (docs/TRACING.md
            # "Control plane"); both spellings like mesh/launch-queue
            self.cct.asok.register_command(
                "pg ledger", self._asok_pg_ledger)
            self.cct.asok.register_command(
                "pg_ledger", self._asok_pg_ledger)
            # wire-plane flight recorder (docs/TRACING.md "Wire
            # plane"); both spellings like mesh/launch-queue
            self.cct.asok.register_command(
                "messenger status", self._asok_messenger_status)
            self.cct.asok.register_command(
                "messenger_status", self._asok_messenger_status)
            self.cct.asok.register_command(
                "conn profile", self._asok_conn_profile)
            self.cct.asok.register_command(
                "conn_profile", self._asok_conn_profile)
        self.store = store or MemStore()
        self.store.mount()
        self._raw_tid = 1 << 32   # raw-RPC tids, disjoint from backends'
        self.raw_write_waiters: dict = {}
        self.raw_list_waiters: dict = {}
        self._recovered_epochs: set[int] = set()
        self.recovery_enabled = True
        self.prev_osdmap: OSDMap | None = None
        # watch/notify (reference osd/Watch.h:48):
        # (pool, oid.name) -> {cookie: conn}
        self.watchers: dict[tuple, dict[int, object]] = {}
        self._notify_id = 0
        self._notify_pending: dict[int, dict] = {}
        self.osdmap = OSDMap()
        self.map_event = threading.Event()
        self.pgs: dict[pg_t, PGState] = {}
        self.pg_lock = threading.RLock()
        self._batch_armed: dict[int, bool] = {}   # backend -> window armed
        from concurrent.futures import ThreadPoolExecutor
        self._op_pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix=f"osd.{osd_id}.op")
        # op scheduler (reference OpScheduler.cc make_scheduler):
        # osd_op_queue=mclock routes client ops through a ShardedOpWQ
        # draining an MClockScheduler — per-class reservation/weight/
        # limit QoS with observable phase + queue-wait counters
        # (mclock.osd.N perf set, docs/QOS.md).  The wpq default keeps
        # the plain executor: same 16-wide worker pool either way.
        self.op_wq = None
        if str(self.cct.conf.get("osd_op_queue")) == "mclock":
            from .scheduler import ShardedOpWQ
            self.op_wq = ShardedOpWQ(
                n_threads=16, kind="mclock", conf=self.cct.conf,
                perf=self.cct.perf.add(
                    PerfCountersBuilder(f"mclock.osd.{osd_id}")
                    .create_perf_counters()))

            def _apply_mclock(_k=None, _v=None):
                self.op_wq.apply_conf(self.cct.conf)
            for _opt in ("osd_mclock_profile",
                         "osd_mclock_custom_profile"):
                self.cct.conf.add_observer(_opt, _apply_mclock)
            if self.cct.asok is not None:
                self.cct.asok.register_command(
                    "dump_mclock", lambda cmd: self.op_wq.dump())
        # PGs whose last recovery pass failed: the steady-state skip
        # must not strand them until an unrelated acting change
        self._pgs_needing_recovery: set = set()
        # led PGs serving with a shard slot that has NO live holder
        # (down-not-out member -> CRUSH_ITEM_NONE hole): everything
        # recoverable is recovered, but redundancy is below target —
        # the reference's active+undersized+degraded.  Counted into
        # MPGStats degraded_pgs (PG_DEGRADED health, mgr progress)
        # and mirrored as an open pg_ledger degraded window; NOT in
        # _pgs_needing_recovery, which gates active+clean waits
        self._pgs_undersized: set = set()
        # recovery passes currently running (quiescence observable for
        # tests/operators: 0 + empty needing-recovery = settled)
        self._recovery_inflight = 0
        self._split_retry_pending = False
        # objects recovery proved unrecoverable with every holder
        # answering (partial writes that never acked, or loss beyond
        # m).  Latched per PG so they stop holding the PG in
        # needing-recovery — the reference's "unfound" state; a later
        # pass re-evaluates (pg_t -> {hobject_t})
        self._unfound: dict[pg_t, set] = {}
        # -- PG split state --------------------------------------------
        # Serializes the local split sweep against shard writes: a
        # sub-write applied concurrently with the sweep could land an
        # object in a parent collection after the sweep passed it, and
        # the shard log mutations (append vs split_out) must not
        # interleave.  Held only across local store work, never across
        # RPCs.  Deliberately one OSD-global lock: the work it covers
        # is Python-level (GIL-bound anyway), and the sweep — the only
        # long holder — is a one-off pause per split, the analog of
        # the reference's pg-lock'd PG::split_into.
        self._split_lock = threading.RLock()
        # child pg -> parent pg recorded when a pool's pg_num grows
        # (the ps-bits ancestry): read/stat fall back through it while
        # a split is settling, and recovery scans ancestor collections
        # for child objects that still sit on pre-split holders
        self._split_ancestry: dict[pg_t, pg_t] = {}
        # (child spg, hobject) moved locally by a split but not yet
        # confirmed on the child's acting home.  The HOLDER drives
        # convergence: a child primary that already ran its recovery
        # pass has no way to learn about objects a lagging holder
        # re-homes later (acked writes racing the map), so the holder
        # pushes and retries until each lands.
        self._split_push_pending: set[tuple[spg_t, hobject_t]] = set()
        self._split_pusher_armed = False
        # PG merge state is deliberately NOT in-memory: dying merge
        # children are derived from the committed map itself
        # (pool.pg_num <= seed < pool.pg_num_max — see _is_dying_pg /
        # _merge_source_pgs), so an OSD that was down across the
        # shrink routes, folds, and recovers identically after revive.
        self.raw_read_waiters: dict = {}
        # shard-resident replicated PG logs (reference: pglog omap keys
        # in the pg meta collection) + peering RPC plumbing
        self.shard_logs: dict = {}
        self.peer_waiters: dict = {}
        # striped per-object op ordering (bounded; rare false sharing
        # is harmless — it only over-serializes)
        self._obj_locks = [threading.Lock() for _ in range(256)]
        self._created_cids: set[spg_t] = set()
        self.heartbeat_interval = heartbeat_interval
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        self._hb_last_seen: dict[int, float] = {}
        self._hb_first_ping: dict[int, float] = {}
        # tick-lag detector state: when the previous heartbeat tick
        # STARTED (perf_counter) — a tick that starts much later than
        # interval after its predecessor means the loop was starved
        # (first-bucket XLA compile holding the GIL, load) and peers
        # may be about to report us down
        self._hb_last_tick: float | None = None
        # MPGStats dedup (last report sent + when): unchanged reports
        # re-send only at the osd_pg_stat_keepalive cadence
        self._pgstats_last_sent: dict | None = None
        self._pgstats_last_time = 0.0

        # reactor pool size is a startup option: the class-level pool
        # is created by the FIRST messenger on this host, so the knob
        # must be applied before construction (vstart does the same
        # for in-process clusters; this covers ProcCluster daemons)
        Messenger.configure_pool(
            int(self.cct.conf.get("ms_async_op_threads")))
        self.messenger = Messenger(f"osd.{osd_id}", auth=auth,
                                   secure=secure)
        self.messenger.add_dispatcher(self._dispatch)
        # wire-plane flight recorder (msg/msgr_ledger.py, docs/
        # TRACING.md "Wire plane"): per-daemon wire counters always
        # register (each daemon's own traffic), but the shared
        # MsgrLedger perf set (reactor lag + dispatch histograms)
        # follows the profiler's perf-owner rule — the pool is a host
        # singleton, so exactly ONE daemon per process exports it and
        # ships the monward lag window on MPGStats
        self.cct.perf.add(self.messenger.stats.perf)
        _mled = self.messenger.ledger
        self._msgr_reporter = False
        if not getattr(_mled, "_perf_registered", False):
            _mled._perf_registered = True
            self._msgr_reporter = True
            self.cct.perf.add(_mled.perf)
        # fast dispatch (reference ms_fast_dispatch): the EC data-path
        # RPCs run inline on the reactor — their handlers never block
        # on nested RPCs (shard read = store read + async send; the
        # reply routers hand off to callbacks/events; ping replies
        # inline; MOSDOp's dispatch is just an op-pool submit).
        # Sub-WRITES stay on the executor (store commit may do real
        # I/O on BlueStore/FileStore).
        self.messenger.fast_dispatch = lambda msg: isinstance(
            msg, (M.MOSDOp, M.MOSDECSubOpRead, M.MOSDECSubOpReadReply,
                  M.MOSDECSubOpWriteReply, M.MOSDPing))
        # fault-injection knobs ride the config system so the thrasher
        # (and injectargs at runtime) can set them per daemon
        # (reference ms_inject_* dev options, options.cc:1071-1092)
        conf = self.cct.conf

        def _apply_inject(_k=None, _v=None):
            self.messenger.inject_socket_failures = \
                int(conf.get("ms_inject_socket_failures"))
            self.messenger.inject_delay_prob = \
                float(conf.get("ms_inject_delay_probability"))
            self.messenger.inject_delay_max = \
                float(conf.get("ms_inject_delay_max"))
            self.messenger.compress_algo = \
                str(conf.get("ms_compress")) or None
            self.messenger.compress_min = \
                int(conf.get("ms_compress_min_size"))
            self.messenger.inject_dispatch_stall = \
                float(conf.get("ms_inject_dispatch_stall"))
            self.messenger.sync_timeout = \
                float(conf.get("ms_sync_timeout"))
        _apply_inject()

        def _apply_msgr(_k=None, _v=None):
            led = self.messenger.ledger
            led.enabled = bool(conf.get("ms_ledger"))
            led.set_peer_cap(int(conf.get("ms_ledger_peers")))
            led.probe_interval = float(
                conf.get("ms_reactor_lag_interval"))
            led.warn_s = float(conf.get("ms_reactor_lag_warn_s"))
        _apply_msgr()
        for _opt in ("ms_ledger", "ms_ledger_peers",
                     "ms_reactor_lag_interval",
                     "ms_reactor_lag_warn_s"):
            conf.add_observer(_opt, _apply_msgr)
        # recovery concurrency cap (reference osd_max_backfills
        # reservations): bounds simultaneous per-object rebuilds
        # across this daemon's recovery threads
        self._recovery_sem = threading.BoundedSemaphore(
            max(1, int(conf.get("osd_max_backfills"))))
        # repair-bandwidth throttle (docs/REPAIR.md): token-bucket
        # timestamp shared by every recovery push on this daemon
        self._rec_throttle_lock = threading.Lock()
        self._rec_next_free = 0.0
        for _opt in ("ms_inject_socket_failures",
                     "ms_inject_delay_probability",
                     "ms_inject_delay_max", "ms_compress",
                     "ms_compress_min_size",
                     "ms_inject_dispatch_stall", "ms_sync_timeout"):
            conf.add_observer(_opt, _apply_inject)
        self.addr = self.messenger.bind(addr)
        # one mon or a monmap list (reference MonClient hunting)
        from ..msg.addrs import normalize_mon_addrs
        self.mon_addrs = normalize_mon_addrs(mon_addr)
        self._mon_idx = 0
        self._last_map_time = time.time()
        self.mon_conn = self.messenger.connect(self.mon_addrs[0])

    # -- lifecycle ----------------------------------------------------------

    def boot(self, timeout: float = 10.0) -> None:
        """reference OSD::init + MOSDBoot."""
        self._maybe_prewarm()
        self.mon_conn.send_message(M.MMonGetMap())
        self.mon_conn.send_message(M.MOSDBoot(self.osd_id, self.addr))
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.osdmap.is_up(self.osd_id):
                break
            self.map_event.wait(0.05)
            self.map_event.clear()
        if self.heartbeat_interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"osd.{self.osd_id}.hb")
            self._hb_thread.start()
        if bool(self.cct.conf.get("osd_scrub_auto")):
            threading.Thread(
                target=self._scrub_loop, daemon=True,
                name=f"osd.{self.osd_id}.scrub").start()
        # always started: osd_enable_op_tracker is live-tunable, so the
        # surveillance loop must exist even when tracking is off at boot
        threading.Thread(
            target=self._optrack_loop, daemon=True,
            name=f"osd.{self.osd_id}.optrack").start()
        # pg stats: the mon-side `pg stat` / PG_DEGRADED / interleave
        # guard all read these periodic reports
        threading.Thread(
            target=self._pgstats_loop, daemon=True,
            name=f"osd.{self.osd_id}.pgstats").start()

    def shutdown(self) -> None:
        self._hb_stop.set()
        self._op_pool.shutdown(wait=False)
        if self.op_wq is not None:
            self.op_wq.drain_and_stop()
        self.messenger.shutdown()
        self.store.umount()
        self.cct.shutdown()

    def conn_to_osd(self, osd: int):
        info = self.osdmap.osds.get(osd)
        if info is None or info.addr is None:
            raise ErasureCodeError(errno.EHOSTUNREACH, f"osd.{osd} unknown")
        return self.messenger.connect(tuple(info.addr))

    # -- dispatch -----------------------------------------------------------

    def _dispatch(self, conn, msg) -> None:
        try:
            # privilege fence (reference OSDCap): with auth on, only
            # service-keyed peers (other daemons, the mon) may speak
            # cluster-internal protocol; clients are limited to the
            # public op surface
            if self.messenger.auth is not None:
                ident = getattr(conn.session, "auth_identity", None)
                kind = ident.get("kind") if ident else "none"
                if kind != "service" and not isinstance(
                        msg, (M.MOSDOp, M.MWatchNotify)):
                    return
            if isinstance(msg, M.MMonMap):
                self._handle_map(msg)
            elif isinstance(msg, M.MOSDMapInc):
                self._handle_map_inc(msg)
            elif isinstance(msg, M.MOSDOp):
                # op tracking starts at messenger dispatch: adopt the
                # client's trace context — same span, the op continues
                # across the wire (docs/TRACING.md).  The enabled gate
                # is out here so the off path skips the description
                # f-string and trace decode entirely (zero per-op cost)
                if self.op_tracker.enabled:
                    top = self.op_tracker.create(
                        "osd_op",
                        f"{msg.oid.name} {[op[0] for op in msg.ops]}",
                        TraceContext.from_wire(msg.trace))
                    top.mark_event("msgr_dispatch",
                                   getattr(msg, "recv_stamp", None))
                    # wire-plane stitch: the interval from recv_stamp
                    # (frame off the socket) to here is the messenger
                    # dispatch-queue wait — blamed on msgr_recv_lag so
                    # a starved executor names itself on the timeline
                    if self.messenger.ledger.enabled:
                        top.mark_event("msgr_recv_lag")
                    top.set_info("pg", str(msg.pgid.pgid))
                    # the op's primary IS this OSD (client ops land on
                    # the primary): slow-op reports carry it so the
                    # mon's SLOW_OPS summary blames the op owner even
                    # when a replica's sub-op report arrives first
                    top.set_info("primary", self.osd_id)
                else:
                    top = NULL_TRACKED
                msg.top = top
                # client ops run on the sharded op pool (reference
                # ShardedOpWQ): the messenger awaits each dispatch per
                # connection, so handling inline would serialize every
                # op of a client behind the previous op's COMMIT —
                # no pipelining, and the batch window could never see
                # two ops.  Per-object ordering still comes from the
                # stripe locks in _handle_client_op.
                top.mark_event("queued")
                if self.op_wq is not None:
                    # mclock path: the op class is the client-declared
                    # QoS class riding the wire (dmclock carries client
                    # info the same way) — but only operator-
                    # provisioned, non-internal classes are honored;
                    # everything else collapses into "client"
                    # (ShardedOpWQ.wire_class_ok).
                    # _handle_client_op_safe marks `dequeued`.
                    qc = getattr(msg, "qos", None)
                    if not qc or not self.op_wq.wire_class_ok(qc):
                        qc = "client"
                    self.op_wq.queue(
                        lambda c=conn, m=msg:
                            self._handle_client_op_safe(c, m),
                        op_class=qc)
                else:
                    self._op_pool.submit(self._handle_client_op_safe,
                                         conn, msg)
            elif isinstance(msg, M.MOSDECSubOpWrite):
                self.perf.inc("subop_w")
                # sub-op span: child of the primary's op span, same
                # trace id — the cross-hop stitch point
                if self.op_tracker.enabled:
                    stop = self.op_tracker.create(
                        "ec_sub_write", f"{msg.pgid} tid={msg.tid}",
                        TraceContext.from_wire(msg.trace))
                    stop.set_info("pg", str(msg.pgid.pgid))
                    # a sub-op belongs to the PG's primary: the mon
                    # attributes SLOW_OPS to the op's owner, not to
                    # whichever replica happened to report first
                    try:
                        stop.set_info(
                            "primary",
                            self.osdmap.pg_to_up_acting_osds(
                                msg.pgid.pgid)[3])
                    except Exception:  # noqa: BLE001 - stale/gap map
                        pass
                else:
                    stop = NULL_TRACKED
                try:
                    self.apply_sub_write(msg.pgid, msg.txn,
                                         msg.log_entries,
                                         msg.at_version,
                                         msg.rollforward_to)
                except Exception:
                    stop.mark_event("failed")
                    self.op_tracker.unregister(stop, -errno.EIO)
                    raise
                stop.mark_event("sub_op_applied")
                conn.send_message(M.MOSDECSubOpWriteReply(
                    msg.pgid, msg.tid, msg.pgid.shard))
                self.op_tracker.unregister(stop, 0)
            elif isinstance(msg, M.MPGLogQuery):
                slog = self._shard_log(msg.pgid)
                from .pg_log import entry_to_wire
                conn.send_message(M.MPGLogReply(
                    msg.pgid, msg.tid, slog.info.to_json(),
                    [entry_to_wire(e) for e in slog.log.entries]))
            elif isinstance(msg, M.MPGLogRollback):
                removed = self._shard_log(msg.pgid).rollback_to(msg.v)
                conn.send_message(M.MPGLogRollbackReply(
                    msg.pgid, msg.tid,
                    [M.hobj_to_json(o) for o in removed]))
            elif isinstance(msg, M.MPGActivate):
                self._handle_activate(msg)
                conn.send_message(M.MPGActivateReply(msg.pgid, msg.tid))
            elif isinstance(msg, (M.MPGLogReply, M.MPGLogRollbackReply,
                                  M.MPGActivateReply)):
                waiter = self.peer_waiters.pop((msg.pgid, msg.tid), None)
                if waiter is not None:
                    waiter(msg)
            elif isinstance(msg, M.MOSDECSubOpRead):
                self.perf.inc("subop_r")
                reply = self.stat_shard(msg.pgid, msg.oid,
                                        msg.want_attrs,
                                        msg.want_omap) \
                    if msg.length == 0 else \
                    self._read_reply(msg.pgid, msg.oid, msg.off, msg.length)
                reply.tid = msg.tid
                conn.send_message(reply)
            elif isinstance(msg, M.MOSDECSubOpWriteReply):
                self._route_write_reply(msg)
            elif isinstance(msg, M.MOSDECSubOpReadReply):
                self._route_read_reply(msg)
            elif isinstance(msg, M.MPGList):
                conn.send_message(M.MPGListReply(
                    msg.pgid, msg.tid, self._list_pg_objects(msg.pgid)))
            elif isinstance(msg, M.MPGListReply):
                waiter = self.raw_list_waiters.pop((msg.pgid, msg.tid), None)
                if waiter is not None:
                    waiter(msg)
            elif isinstance(msg, M.MWatchNotify) and msg.is_ack:
                pend = self._notify_pending.get(msg.notify_id)
                if pend is not None:
                    pend["remaining"].discard(msg.cookie)
                    if not pend["remaining"]:
                        pend["event"].set()
            elif isinstance(msg, M.MOSDPing):
                self._handle_ping(conn, msg)
        except Exception as e:  # noqa: BLE001 - daemon must not die
            if isinstance(msg, M.MOSDOp):
                self._reply_op_error(conn, msg, e)
            elif getattr(e, "errno", None) != errno.EAGAIN:
                # cluster-internal paths send no error reply; a
                # swallowed traceback here would hide real bugs
                import traceback
                traceback.print_exc()

    def _apply_mon_config(self, config: dict) -> None:
        """Central config (reference ConfigMonitor/MConfig): the mon
        piggybacks its config_db sections on every map publish; the
        'global' < 'osd' < 'osd.N' sections become this daemon's 'mon'
        config layer, so `ceph config set` / `osd mclock profile set`
        reach running daemons without a restart."""
        merged: dict = {}
        for section in ("global", "osd", f"osd.{self.osd_id}"):
            merged.update(config.get(section, {}))
        try:
            self.cct.conf.apply_mon_layer(merged)
        except Exception:  # noqa: BLE001 - a bad central value must
            # never take the map-handling path down with it
            import traceback
            traceback.print_exc()

    def _handle_map(self, msg: M.MMonMap) -> None:
        self._last_map_time = time.time()
        # config rides every publish, even ones whose osdmap epoch we
        # already have (a pure `config set` doesn't bump the osdmap)
        if "config" in msg.map_json:
            self._apply_mon_config(msg.map_json["config"] or {})
        self._adopt_map(OSDMap.from_json(msg.map_json))

    def _handle_map_inc(self, msg: M.MOSDMapInc) -> None:
        """Incremental map range or keepalive ack (reference the OSD's
        handling of MOSDMap incremental epochs): apply the committed
        delta chain on top of our map — bit-equal to full-map adoption
        — and fall back to an explicit full-map request on any epoch
        gap (we slept past the mon's incremental ring, or the mon's
        optimistic tracking overshot us)."""
        self._last_map_time = time.time()
        # config is authoritative on EVERY send (an emptied config_db
        # must clear the mon layer, exactly like the MMonMap path)
        self._apply_mon_config(msg.config or {})
        if not msg.incs:
            # keepalive: the mon believes we are current.  If it acks
            # an epoch AHEAD of us its tracking overshot (a send we
            # never got) — recover with a full request.
            if msg.epoch > self.osdmap.epoch:
                self._request_full_map()
            else:
                self.map_event.set()
            return
        m = apply_inc_chain(self.osdmap, msg.incs)
        if m is None:               # gap -> explicit full re-request
            self._request_full_map()
            return
        self._adopt_map(m)

    def _request_full_map(self) -> None:
        try:     # have_epoch=0: the mon must answer with a full map
            self.mon_conn.send_message(M.MMonGetMap())
        except Exception:  # noqa: BLE001 - mon hunting handles it
            pass

    def _adopt_map(self, newmap: OSDMap) -> None:
        if newmap.epoch <= self.osdmap.epoch and self.osdmap.epoch:
            self.map_event.set()
            return
        self.prev_osdmap = self.osdmap if self.osdmap.epoch else None
        # peers that (re)joined start their heartbeat clock fresh
        for oid_, o in newmap.osds.items():
            if o.up and not (self.prev_osdmap is not None and
                             self.prev_osdmap.is_up(oid_)):
                self._hb_last_seen.pop(oid_, None)
                self._hb_first_ping.pop(oid_, None)
        # PG split/merge detection: pools whose pg_num changed.
        # Record the ps-bits ancestry BEFORE adopting the map so
        # concurrent reads/stats that miss in a child (split) or
        # parent (merge) collection can already fall back while the
        # sweep runs.
        grown: list[tuple[int, int, int]] = []
        shrunk: list[tuple[int, int, int]] = []
        if self.prev_osdmap is not None:
            for pid, pool in newmap.pools.items():
                old = self.prev_osdmap.pools.get(pid)
                if old is None:
                    continue
                if pool.pg_num > old.pg_num:
                    grown.append((pid, old.pg_num, pool.pg_num))
                    for c in range(old.pg_num, pool.pg_num):
                        self._split_ancestry[pg_t(pid, c)] = \
                            pg_t(pid, c % old.pg_num)
                elif pool.pg_num < old.pg_num:
                    shrunk.append((pid, old.pg_num, pool.pg_num))
        else:
            # first map after (re)boot: a split OR merge may have
            # committed while this OSD was down — its collections
            # would still hold pre-resize placement.  Rehash every
            # pool's local collections and fold any stale
            # beyond-pg_num child collections (no-op when nothing is
            # misplaced; one boot-time hash per local object.  A
            # persisted per-pool pg_num marker could skip this
            # entirely — future work if boot time on large persistent
            # stores ever matters).
            grown = [(pid, pool.pg_num, pool.pg_num)
                     for pid, pool in newmap.pools.items()]
            shrunk = [(pid, pool.pg_num, pool.pg_num)
                      for pid, pool in newmap.pools.items()]
        self.osdmap = newmap
        # refresh acting sets of cached backends; an interval change
        # (acting set differs) forces re-peering before the next op
        # (reference PeeringState start_peering_interval)
        resized_pools = {pid for pid, _o, _n in grown} | \
            {pid for pid, old_n, new_n in shrunk if old_n != new_n}
        with self.pg_lock:
            # dying merge children stop existing: their recovery /
            # unfound bookkeeping must not wedge quiescence
            for pid, old_n, new_n in shrunk:
                if old_n == new_n:
                    continue
                self._pgs_needing_recovery = {
                    p for p in self._pgs_needing_recovery
                    if not (p.pool == pid and p.seed >= new_n)}
                for p in [p for p in self._pgs_undersized
                          if p.pool == pid and p.seed >= new_n]:
                    self._pgs_undersized.discard(p)
                    self.pg_ledger.degraded_close(p)
                for p in [p for p in self._unfound
                          if p.pool == pid and p.seed >= new_n]:
                    self._unfound.pop(p, None)
            for pgid, state in list(self.pgs.items()):
                if pgid.pool in resized_pools:
                    # a resize is a new interval for every PG of the
                    # pool: parents change content, children are born
                    # or die — rebuild (and re-peer) on next use
                    self.pgs.pop(pgid, None)
                    self.pg_ledger.transition(pgid, "interval_change",
                                              epoch=newmap.epoch)
                    continue
                up, acting, _, primary = newmap.pg_to_up_acting_osds(pgid)
                shards = getattr(state.backend, "shards", None) or \
                    getattr(state.backend, "replicas", None)
                if hasattr(shards, "acting"):
                    if list(acting) != list(shards.acting):
                        state.needs_peer = True
                        self.pg_ledger.transition(
                            pgid, "interval_change",
                            epoch=newmap.epoch)
                    shards.acting = list(acting)
                    if state.kind != "ec":
                        # replicated width follows the acting set
                        shards.n_replicas = len(shards.acting)
                if primary != self.osd_id:
                    self.pgs.pop(pgid, None)  # primary moved away
        # a running OSD the map says is down re-announces itself —
        # heartbeat-grace flaps on a loaded host would otherwise leave
        # it marked down forever (reference OSD::_committed_osd_maps
        # re-sends MOSDBoot when !osdmap->is_up(whoami))
        if not self._hb_stop.is_set() and self.osd_id in newmap.osds \
                and not newmap.is_up(self.osd_id):
            try:
                self.mon_conn.send_message(
                    M.MOSDBoot(self.osd_id, self.addr))
            except Exception:  # noqa: BLE001 - mon hunting handles it
                pass
        # split local shard collections BEFORE the recovery pass for
        # this epoch: recovery must see objects in their post-split
        # homes (remote stragglers are found via ancestor scans)
        for pid, old_n, new_n in grown:
            try:
                self._split_pool_collections(pid, new_n)
            except Exception:  # noqa: BLE001 - a failed sweep must not
                # kill dispatch; the misplaced-write/read fallbacks and
                # recovery retries converge the leftovers
                import traceback
                traceback.print_exc()
        # fold dying merge children into their parents, likewise
        # before recovery (the parent primary's pass must see folded
        # objects locally; remote stragglers come via child scans)
        for pid, old_n, new_n in shrunk:
            # boot-time rehash folds silently; a live shrink is a
            # tracked op (docs/TRACING.md `merge` stages)
            top = self.op_tracker.create(
                "merge", f"pool={pid} {old_n}->{new_n}") \
                if old_n != new_n else NULL_TRACKED
            try:
                self._merge_pool_collections(pid, new_n)
                top.mark_event("merge_done")
            except Exception:  # noqa: BLE001 - same containment as
                top.mark_event("failed")        # the split sweep
                import traceback
                traceback.print_exc()
            finally:
                self.op_tracker.unregister(top)
            if old_n == new_n:
                continue      # boot-time rehash, not a live shrink
            # every surviving parent this OSD leads re-runs the wide
            # recovery scan: lagging holders' child collections may
            # still hold acked data the fold hasn't delivered
            with self.pg_lock:
                for seed in range(new_n):
                    pgid = pg_t(pid, seed)
                    try:
                        _, _, _, primary = \
                            newmap.pg_to_up_acting_osds(pgid)
                    except Exception:  # noqa: BLE001
                        continue
                    if primary == self.osd_id:
                        self._pgs_needing_recovery.add(pgid)
                        self.pg_ledger.transition(
                            pgid, "needs_recovery",
                            epoch=newmap.epoch)
        self.map_event.set()
        if self.recovery_enabled and newmap.pools and \
                newmap.epoch not in self._recovered_epochs:
            self._recovered_epochs.add(newmap.epoch)
            # snapshot the previous map NOW: by the time the thread
            # runs, self.prev_osdmap may already be a newer epoch and
            # the changed-acting comparison would look at the wrong
            # interval
            threading.Thread(target=self._recover_epoch,
                             args=(newmap.epoch, self.prev_osdmap),
                             daemon=True,
                             name=f"osd.{self.osd_id}.recovery").start()

    # -- recovery / backfill (reference PeeringState -> Recovering /
    #    Backfilling; ECBackend::continue_recovery_op :570) ----------------

    def _recover_epoch(self, epoch: int, prevmap=None) -> None:
        """After a map change, rebuild any shard the new acting set is
        missing, for every PG this OSD leads.  This is the elastic part
        of the system: mark an OSD out -> CRUSH picks replacements ->
        primaries reconstruct the lost shards onto them."""
        with self.pg_lock:
            self._recovery_inflight += 1
        top = self.op_tracker.create("recovery", f"epoch={epoch}")
        try:
            self._recover_epoch_inner(epoch, prevmap)
            top.mark_event("recovery_done")
        finally:
            self.op_tracker.unregister(top)
            with self.pg_lock:
                self._recovery_inflight -= 1
        # Convergence timer: a failed/partial recovery (split sources
        # lagging, a push that timed out, peers briefly saturated) used
        # to wait for the NEXT map epoch — and a quiet cluster produces
        # none, stranding the PG until an unrelated acting change.
        # Retry on a timer until the set drains — but only for PGs
        # whose acting set is fully up: a retry against a down member
        # can't complete anyway, the revival bumps an epoch that
        # recovers normally, and full-scan retry passes against dead
        # peers starve live traffic mid-thrash.  One pending retry at
        # a time, 5s apart.
        # Armed on CURRENT state, not `epoch == self.osdmap.epoch`: a
        # pass for a stale epoch can be the LAST one to touch the
        # needing set (a newer epoch's pass may already have finished
        # while this one was mid-scan), and skipping the arm then
        # strands the set until an unrelated map change.
        if not self._hb_stop.is_set() and self._pgs_needing_recovery \
                and self._retry_could_help():
            with self.pg_lock:
                if self._split_retry_pending:
                    return
                self._split_retry_pending = True

            def _retry():
                with self.pg_lock:
                    self._split_retry_pending = False
                # recover against the CURRENT epoch: an epoch that
                # landed inside the retry window must not swallow the
                # retry (its own pass may already have run and failed
                # before this timer armed)
                if not self._hb_stop.is_set() and \
                        self._pgs_needing_recovery:
                    self._recover_epoch(self.osdmap.epoch, self.osdmap)

            t = threading.Timer(5.0, _retry)
            t.daemon = True
            t.start()

    def _retry_could_help(self) -> bool:
        """A recovery retry is worth scheduling iff some PG in the
        needing-recovery set has every acting member up."""
        from ..crush.map import CRUSH_ITEM_NONE
        for pgid in list(self._pgs_needing_recovery):
            try:
                _, acting, _, _ = self.osdmap.pg_to_up_acting_osds(pgid)
            except Exception:  # noqa: BLE001
                continue
            if acting and all(o != CRUSH_ITEM_NONE and
                              self.osdmap.is_up(o) for o in acting):
                return True
        return False

    def _recover_epoch_inner(self, epoch: int, prevmap=None) -> None:
        import numpy as np
        from ..store.object_store import Transaction
        # prune needing-recovery/unfound entries for PGs the map no
        # longer has (pool deleted, or a merge folded the child away)
        # or that another OSD now leads (recovery passes only process
        # led PGs, so a non-led entry can never clear) — a stale
        # entry would wedge quiescence forever
        def still_ours(p: pg_t) -> bool:
            pool = self.osdmap.pools.get(p.pool)
            if pool is None or p.seed >= pool.pg_num:
                return False
            try:
                _, _, _, primary = self.osdmap.pg_to_up_acting_osds(p)
            except Exception:  # noqa: BLE001 - unmappable: keep
                return True
            return primary == self.osd_id or primary < 0
        with self.pg_lock:
            self._pgs_needing_recovery = {
                p for p in self._pgs_needing_recovery if still_ours(p)}
            gone_undersized = [p for p in self._pgs_undersized
                               if not still_ours(p)]
            self._pgs_undersized.difference_update(gone_undersized)
            for p in [p for p in self._unfound
                      if p.pool not in self.osdmap.pools or
                      p.seed >= self.osdmap.pools[p.pool].pg_num]:
                self._unfound.pop(p, None)
        for p in gone_undersized:
            # the window moved with the PG (new primary re-opens its
            # own); a window left open here would leak the gauge
            self.pg_ledger.degraded_close(p)
        # peers that time out once in this pass are not probed again:
        # a dead-but-still-up OSD must not cost 3s per object/shard
        unreachable: set[int] = set()
        for pool in list(self.osdmap.pools.values()):
            for seed in range(pool.pg_num):
                if self._hb_stop.is_set():   # daemon shut down mid-pass
                    return
                pgid = pg_t(pool.id, seed)
                try:
                    up, acting, _, primary = \
                        self.osdmap.pg_to_up_acting_osds(pgid)
                except Exception:  # noqa: BLE001
                    continue
                if primary != self.osd_id:
                    continue
                try:
                    if pool.is_erasure():
                        # one reservation per PG recovery (reference
                        # osd_max_backfills: concurrent backfilling PGs)
                        with self._recovery_sem:
                            self._run_recovery_op(
                                lambda: self._recover_ec_pg(
                                    pgid, acting, unreachable, prevmap))
                    else:
                        with self._recovery_sem:
                            self._run_recovery_op(
                                lambda: self._recover_replicated_pg(
                                    pgid, acting, prevmap, unreachable))
                except ErasureCodeError as e:
                    # peering-incomplete (EAGAIN) or similar on ONE PG
                    # must not kill the recovery pass for the rest —
                    # but a later steady-state epoch must retry it.
                    # Re-check leadership on the LIVE map first: if the
                    # primary moved mid-pass ("not primary" EAGAIN),
                    # adding the pg here would re-wedge the needing set
                    # a newer epoch's pass already pruned — and with no
                    # further epochs coming, quiescence never clears.
                    try:
                        _, _, _, cur_primary = \
                            self.osdmap.pg_to_up_acting_osds(pgid)
                    except Exception:  # noqa: BLE001
                        cur_primary = self.osd_id
                    if cur_primary == self.osd_id or cur_primary < 0:
                        self._pgs_needing_recovery.add(pgid)
                    self.cct.dout("osd", 2,
                                  f"recovery of {pgid} deferred: {e}")

    # -- prioritized recovery (docs/REPAIR.md, docs/QOS.md) -----------------

    def _run_recovery_op(self, fn) -> None:
        """Route one background rebuild unit (a PG's recovery pass)
        through the scheduler's `recovery` class: with osd_op_queue=
        mclock the unit dequeues under the recovery reservation/limit
        triple — degraded-object client reads (which arrive as client-
        class ops and reconstruct inline) preempt rebuild work instead
        of queueing behind it.  Without the mClock queue the unit runs
        inline on the recovery pass thread, as before."""
        if self.op_wq is None:
            fn()
            return
        done = threading.Event()
        box: dict = {}

        def thunk():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — re-raised below
                box["err"] = e
            finally:
                done.set()
        self.op_wq.queue(thunk, op_class="recovery")
        self.perf.inc("recovery_queued_ops")
        # the pass thread paces on the scheduler: wake periodically so
        # daemon teardown never hangs on a drained queue
        while not done.wait(0.5):
            if self._hb_stop.is_set():
                return
        if "err" in box:
            raise box["err"]

    def _recovery_throttle(self, nbytes: int) -> None:
        """Repair-bandwidth brake on rebuilt-shard pushes: a token
        bucket at osd_recovery_max_bytes_per_sec (0 = unlimited) plus
        the coarse osd_recovery_sleep pause.  Applied ONLY to
        background pushes — reconstruct-on-read serves client reads
        inline and never waits here."""
        import time as _time
        sleep = float(self.cct.conf.get("osd_recovery_sleep") or 0.0)
        rate = int(self.cct.conf.get(
            "osd_recovery_max_bytes_per_sec") or 0)
        wait = sleep
        if rate > 0:
            with self._rec_throttle_lock:
                now = _time.monotonic()
                base = max(now, self._rec_next_free)
                wait += max(0.0, base - now)
                self._rec_next_free = base + nbytes / rate
        if wait <= 0:
            return
        self.perf.tinc("recovery_throttle_wait", wait)
        deadline = _time.monotonic() + wait
        while not self._hb_stop.is_set():
            left = deadline - _time.monotonic()
            if left <= 0:
                break
            _time.sleep(min(left, 0.2))

    def _pg_object_names(self, pgid: pg_t, acting, shard_ids,
                         unreachable: set | None = None) -> set:
        names: set = set()
        for s in shard_ids:
            osd = acting[s] if s < len(acting) else None
            if osd is None:
                continue
            from ..crush.map import CRUSH_ITEM_NONE
            if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd):
                continue
            if unreachable is not None and osd in unreachable:
                continue
            spg = spg_t(pgid, s if len(shard_ids) > 1 else NO_SHARD)
            for oj in self._remote_list(osd, spg,
                                        unreachable=unreachable):
                names.add(M.hobj_from_json(oj))
        # keep only names the ps-bits rule assigns to this PG: while a
        # split settles, a lagging holder's parent collection still
        # lists objects that now belong to children — recovery/scrub of
        # the parent must not adopt them back
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is not None and pool.pg_num:
            names = {h for h in names
                     if crush_hash32(h.key or h.name) % pool.pg_num ==
                     pgid.seed}
        return names

    def _list_pg_objects(self, spg: spg_t) -> list:
        """Enumerate user objects of a shard collection, hiding the
        per-PG log/info meta object (the reference keeps pg metadata in
        a separate meta collection; here it's a reserved name) and
        rollback generations (reference ghobject NO_GEN filtering in
        collection_list)."""
        from .pg_log import PG_META_NAME
        from .types import NO_GEN
        try:
            return [M.hobj_to_json(g.hobj)
                    for g in self.store.list_objects(self._cid(spg))
                    if g.hobj.name != PG_META_NAME
                    and g.generation == NO_GEN]
        except KeyError:
            return []

    def _remote_list(self, osd: int, spg: spg_t,
                     timeout: float = 10.0,
                     unreachable: set | None = None) -> list:
        if self._hb_stop.is_set():
            return []          # daemon shut down: no more RPC waits
        if osd == self.osd_id:
            return self._list_pg_objects(spg)
        if unreachable is not None and osd in unreachable:
            return []
        # the O(peers) cost item 4 names: one remote listing RPC per
        # (shard, candidate holder) per re-peered PG
        self.pg_ledger.count(spg.pgid, "remote_lists")
        with self.pg_lock:
            self._raw_tid += 1
            tid = self._raw_tid
        box: dict = {}
        ev = threading.Event()
        self.raw_list_waiters[(spg, tid)] = \
            lambda m: (box.update(oids=m.oids), ev.set())
        try:
            self.conn_to_osd(osd).send_message(M.MPGList(spg, tid))
        except Exception:  # noqa: BLE001
            return []
        if not ev.wait(timeout) and unreachable is not None:
            unreachable.add(osd)
        return box.get("oids", [])

    def _make_recovery_push(self, pgid: pg_t, acting: list[int],
                            oid: hobject_t):
        """Shared recovery sink: write a rebuilt shard chunk (+ its
        integrity attrs) to its acting home (used by epoch recovery and
        post-peering repair)."""
        from .ec_util import recovery_attrs

        def push(s, data, hinfo):
            # background rebuild pays the repair-bandwidth throttle
            # BEFORE the push so a tiny cap can't be overshot by a
            # burst of already-decoded shards (docs/REPAIR.md).  The
            # ledger times the whole throttle gate (not just the
            # sleep): the blame row's throttle_s is the time pushes
            # spent in the brake, positive whenever pushes ran
            with self.pg_ledger.stage(pgid, "throttle"):
                self._recovery_throttle(int(np.asarray(data).size))
            txn = Transaction()
            goid = shard_oid(oid, s)
            txn.write(goid, 0, data)
            txn.setattrs(goid, recovery_attrs(hinfo, data))
            # count only DELIVERED bytes: a push that times out on a
            # dead peer must not inflate the repair ledger
            with self.pg_ledger.stage(pgid, "push"):
                delivered = self._push_shard_txn(acting[s],
                                                 spg_t(pgid, s), txn)
            if delivered:
                self.perf.inc("recovery_pushed_bytes",
                              int(np.asarray(data).size))
        return push

    def _push_shard_txn(self, osd: int, spg: spg_t, txn,
                        timeout: float = 20.0) -> bool:
        if self._hb_stop.is_set():
            return False
        if osd == self.osd_id:
            self.apply_shard_txn(spg, txn)
            return True
        with self.pg_lock:
            self._raw_tid += 1
            tid = self._raw_tid
        ev = threading.Event()
        self.raw_write_waiters[(spg, tid)] = lambda m: ev.set()
        self.conn_to_osd(osd).send_message(
            M.MOSDECSubOpWrite(spg, tid, eversion_t(), txn))
        return ev.wait(timeout)

    def _remote_read_full(self, osd: int, spg: spg_t, oid: hobject_t,
                          timeout: float = 3.0,
                          unreachable: set | None = None,
                          want_omap: bool = False,
                          stat_only: bool = False):
        if self._hb_stop.is_set():
            return None
        """(data, attrs) — plus (omap, omap_header) when want_omap —
        of a shard object on a specific OSD, or None.  The backfill
        copy path: a moved shard is fetched from its old holder
        verbatim instead of being re-decoded.  stat_only skips the
        data read (data comes back None): attrs and omap ride the
        stat reply, which is all a version probe needs."""
        if osd == self.osd_id:
            goid = ghobject_t(oid, shard=spg.shard)
            try:
                data = None if stat_only else \
                    self.store.read(self._cid(spg), goid)
                if stat_only:
                    self.store.stat(self._cid(spg), goid)
                attrs = self.store.getattrs(self._cid(spg), goid)
                if want_omap:
                    omap = self.store.omap_get(self._cid(spg), goid)
                    hdr = self.store.omap_get_header(self._cid(spg),
                                                     goid)
            except KeyError:
                return None
            if want_omap:
                return (data if data is None else np.asarray(data),
                        attrs, omap, hdr)
            return (data if data is None else np.asarray(data), attrs)
        with self.pg_lock:
            self._raw_tid += 1
            tid = self._raw_tid
        box: dict = {}
        ev = threading.Event()
        self.raw_read_waiters[(spg, tid)] = \
            lambda m: (box.update(msg=m), ev.set())
        try:
            self.conn_to_osd(osd).send_message(
                M.MOSDECSubOpRead(spg, tid, oid, 0, 0, want_attrs=True,
                                  want_omap=want_omap))
        except Exception:  # noqa: BLE001
            return None
        if not ev.wait(timeout):
            if unreachable is not None:
                unreachable.add(osd)
            return None
        stat = box["msg"]
        if stat.result != 0 or stat.size < 0:
            return None
        if stat_only:
            data = None
        elif stat.size == 0:
            data = np.empty(0, dtype=np.uint8)
        else:
            with self.pg_lock:
                self._raw_tid += 1
                tid = self._raw_tid
            box2: dict = {}
            ev2 = threading.Event()
            self.raw_read_waiters[(spg, tid)] = \
                lambda m: (box2.update(msg=m), ev2.set())
            self.conn_to_osd(osd).send_message(
                M.MOSDECSubOpRead(spg, tid, oid, 0, stat.size))
            if not ev2.wait(timeout) or box2["msg"].result != 0:
                return None
            data = np.frombuffer(box2["msg"].data, dtype=np.uint8)
        if want_omap:
            return data, stat.attrs, stat.omap, stat.omap_header
        return data, stat.attrs

    def _recover_ec_pg(self, pgid: pg_t, acting: list[int],
                       unreachable: set | None = None,
                       prevmap=None) -> None:
        from ..crush.map import CRUSH_ITEM_NONE
        from ..store.object_store import Transaction
        state = self._get_pg(pgid)
        if state.kind != "ec":
            return
        be = state.backend
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None:
            return
        self._unfound.pop(pgid, None)   # re-evaluate each pass
        prevmap = prevmap if prevmap is not None else self.prev_osdmap
        prev_acting = None
        if prevmap is not None and pgid.pool in prevmap.pools:
            try:
                _, prev_acting, _, _ = \
                    prevmap.pg_to_up_acting_osds(pgid)
            except Exception:  # noqa: BLE001
                prev_acting = None
            if pgid.seed >= prevmap.pools[pgid.pool].pg_num:
                # split child born this interval: the previous map's
                # CRUSH answer for its seed is not history — force the
                # full scan so objects are pulled off pre-split holders
                prev_acting = None
        if pgid in self._pgs_needing_recovery:
            # retrying (e.g. split sources lagged last pass): the
            # steady-state shortcuts would scan nothing new
            prev_acting = None
        # objects may live on old holders only: list those too.  Map
        # history beyond one epoch isn't kept (the reference consults
        # past_intervals), so when the acting set changed, the shard
        # scan widens to every up OSD — a moved shard is findable
        # wherever CRUSH last put it.  Steady-state (acting == prev)
        # PGs skip the wide scan.
        unreachable = unreachable if unreachable is not None else set()
        if prev_acting is not None and \
                list(prev_acting) == list(acting) and \
                pgid not in self._pgs_needing_recovery and \
                all(o != CRUSH_ITEM_NONE and self.osdmap.is_up(o)
                    for o in acting):
            # steady state: this PG didn't move and every member is
            # up — writes maintain shards synchronously, so there is
            # nothing to recover.  Skipping saves n_shards remote
            # listings per PG per epoch (a map bump for an unrelated
            # pool was costing every OSD a full listing sweep).
            return
        up_osds = [o.id for o in self.osdmap.osds.values()
                   if o.up and o.id not in unreachable]
        self.pg_ledger.transition(pgid, "recovering",
                                  epoch=self.osdmap.epoch)
        with self.pg_ledger.stage(pgid, "scan"):
            names = self._pg_object_names(pgid, acting, range(be.n),
                                          unreachable=unreachable)
            if prev_acting:
                for s, osd in enumerate(prev_acting):
                    if osd != CRUSH_ITEM_NONE and \
                            self.osdmap.is_up(osd) \
                            and osd not in unreachable:
                        for oj in self._remote_list(
                                osd, spg_t(pgid, s),
                                unreachable=unreachable):
                            names.add(M.hobj_from_json(oj))
            # wide scan only for shards whose holder changed or is
            # gone — steady-state shards are already listed from
            # acting above
            def shard_moved(s: int) -> bool:
                cur = acting[s] if s < len(acting) else CRUSH_ITEM_NONE
                if cur == CRUSH_ITEM_NONE or \
                        not self.osdmap.is_up(cur):
                    return True
                if prev_acting is None:
                    return True
                prev = prev_acting[s] if s < len(prev_acting) \
                    else CRUSH_ITEM_NONE
                return prev != cur
            for s in range(be.n):
                if not shard_moved(s):
                    continue
                spg = spg_t(pgid, s)
                known = {acting[s] if s < len(acting) else None,
                         prev_acting[s] if prev_acting and
                         s < len(prev_acting) else None}
                for osd in up_osds:
                    if osd in known:
                        continue
                    for oj in self._remote_list(osd, spg, timeout=3.0):
                        names.add(M.hobj_from_json(oj))
            # split child / merge parent: objects may still sit in
            # ANCESTOR collections (split) or dying-CHILD collections
            # (merge) on holders whose local sweep lags — list those
            # too, keeping only names the ps-bits rule assigns to
            # this PG
            ancestors = (self._split_ancestors(pgid) +
                         self._merge_source_pgs(pgid)) \
                if prev_acting is None else []
            names |= self._names_from_ancestors(pgid, ancestors,
                                                range(be.n),
                                                pool.pg_num,
                                                up_osds, unreachable)
            if pool.pg_num:
                names = {h for h in names
                         if crush_hash32(h.key or h.name) %
                         pool.pg_num == pgid.seed}
        self.pg_ledger.count(pgid, "objects_scanned", len(names))
        all_ok = True
        # decode-needing objects are DEFERRED and rebuilt in one
        # batched pass after the sweep: grouped by recovery geometry,
        # an OSD-loss storm becomes a handful of distributed decode
        # launches on the mesh plane (or concatenated host decodes)
        # instead of a per-object crawl — docs/MULTICHIP.md
        decode_queue: list[tuple] = []
        for oid in names:
            if self._hb_stop.is_set():
                return
            missing = []
            for s, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE or not self.osdmap.is_up(osd):
                    continue
                if be.shards.stat(s, oid) is None:
                    missing.append(s)
            if not missing:
                continue
            if not self._recover_object(pgid, acting, be, prev_acting,
                                        up_osds, oid, missing,
                                        unreachable,
                                        src_pgs=[pgid] + ancestors,
                                        decode_queue=decode_queue):
                all_ok = False
        if decode_queue:
            with self.pg_ledger.stage(pgid, "decode"):
                if not self._recover_decode_batch(pgid, acting, be,
                                                  decode_queue):
                    all_ok = False
        if all_ok:
            self._pgs_needing_recovery.discard(pgid)
            self._note_pg_redundancy(pgid, acting, be.n)
        else:
            self._pgs_needing_recovery.add(pgid)
            self.pg_ledger.transition(pgid, "recovery_deferred",
                                      epoch=self.osdmap.epoch)
            self.pg_ledger.degraded_open(pgid)

    def _note_pg_redundancy(self, pgid: pg_t, acting: list[int],
                            width: int) -> None:
        """After a clean recovery pass: a shard slot with no live
        holder (down-not-out member) means the PG serves BELOW full
        redundancy even though nothing more is recoverable — track it
        undersized (MPGStats degraded_pgs) with an open degraded
        window until the map gives the slot a home."""
        from ..crush.map import CRUSH_ITEM_NONE
        holes = len(acting) < width or any(
            o == CRUSH_ITEM_NONE or not self.osdmap.is_up(o)
            for o in acting)
        if holes:
            with self.pg_lock:
                self._pgs_undersized.add(pgid)
            self.pg_ledger.transition(pgid, "active_undersized",
                                      epoch=self.osdmap.epoch)
            self.pg_ledger.degraded_open(pgid)
        else:
            with self.pg_lock:
                self._pgs_undersized.discard(pgid)
            self.pg_ledger.transition(pgid, "clean",
                                      epoch=self.osdmap.epoch)
            self.pg_ledger.degraded_close(pgid)

    def _recover_decode_batch(self, pgid, acting, be,
                              decode_queue: list[tuple]) -> bool:
        """Reconstruct-from-k for every deferred object of one PG in
        grouped decode launches (ECBackend.recover_shards_batch)."""
        try:
            results = be.recover_shards_batch(
                decode_queue,
                lambda oid: self._make_recovery_push(pgid, acting,
                                                     oid))
        except Exception as e:  # noqa: BLE001 — whole-batch failure
            self.cct.dout("osd", 1,
                          f"batched recovery of pg {pgid} failed: "
                          f"{e!r}")
            return False
        ok = True
        for oid, err in results.items():
            if err is None:
                self.pg_ledger.count(pgid, "objects_recovered")
                self.cct.dout("osd", 5,
                              f"recovered {oid.name} of pg {pgid} by "
                              f"batched decode")
            else:
                ok = False
                self.cct.dout("osd", 1,
                              f"recovery of {oid.name} failed: {err!r}")
        return ok

    def _names_from_ancestors(self, pgid: pg_t, ancestors, shard_ids,
                              pg_num: int, up_osds,
                              unreachable) -> set:
        """Child-PG object names still listed under ancestor
        collections on any up OSD (their local split sweeps may lag),
        filtered to the names the ps-bits rule assigns to pgid."""
        names: set = set()
        sids = list(shard_ids)
        for anc in ancestors:
            for s in sids:
                aspg = spg_t(anc, s if len(sids) > 1 else NO_SHARD)
                for osd in up_osds:
                    if unreachable is not None and osd in unreachable:
                        continue
                    for oj in self._remote_list(
                            osd, aspg, timeout=3.0,
                            unreachable=unreachable):
                        h = M.hobj_from_json(oj)
                        if crush_hash32(h.key or h.name) % pg_num == \
                                pgid.seed:
                            names.add(h)
        return names

    def _recover_object(self, pgid, acting, be, prev_acting, up_osds,
                        oid, missing, unreachable=None,
                        src_pgs=None, decode_queue=None) -> bool:
        """Rebuild one object's missing shards: backfill-by-copy from
        any surviving holder, else reconstruct-from-k (runs under the
        osd_max_backfills reservation).  src_pgs lists the PGs whose
        collections may hold the shard (the PG itself plus, after a
        split, its ancestors on not-yet-swept holders).  When
        decode_queue is given, objects needing the decode path are
        appended there instead of decoded inline — the caller rebuilds
        the whole queue in grouped (mesh-collective) launches."""
        # 1: backfill-by-copy from wherever the shard still lives
        # (previous holder first, then any up OSD).  A leftover
        # copy from an older interval could be stale, so candidates
        # must match the authoritative hinfo's chunk crc when one
        # is known (reference verifies pushed chunks the same way,
        # ECBackend.cc:991).
        from ..common import crc32c as _crc
        from ..crush.map import CRUSH_ITEM_NONE
        auth_hinfo = be._fetch_hinfo(oid)
        src_pgs = src_pgs or [pgid]
        still_missing = []
        for s in missing:
            copied = False
            candidates: list[int] = []
            if prev_acting and s < len(prev_acting):
                old = prev_acting[s]
                if old != CRUSH_ITEM_NONE and old != acting[s] and \
                        self.osdmap.is_up(old):
                    candidates.append(old)
            candidates.extend(o for o in up_osds
                              if o != acting[s] and
                              o not in candidates)
            for old in candidates:
                if unreachable is not None and old in unreachable:
                    continue
                got = None
                for src_pg in src_pgs:
                    got = self._remote_read_full(
                        old, spg_t(src_pg, s), oid,
                        unreachable=unreachable)
                    if got is not None:
                        break
                if got is None:
                    continue
                data, attrs = got
                if auth_hinfo is not None and (
                        auth_hinfo.total_chunk_size != data.size or
                        (auth_hinfo.crc_valid and
                         _crc.crc32c(data.tobytes(), 0xFFFFFFFF) !=
                         auth_hinfo.get_chunk_hash(s))):
                    continue   # stale leftover from an older interval
                if auth_hinfo is not None and \
                        not auth_hinfo.crc_valid:
                    # overwritten object: at least require the
                    # candidate to match its own chunk_crc (bitrot)
                    from .ec_util import CHUNK_CRC_KEY
                    cc = (attrs or {}).get(CHUNK_CRC_KEY)
                    if cc is not None and \
                            int.from_bytes(cc, "little") != \
                            _crc.crc32c(data.tobytes(), 0xFFFFFFFF):
                        continue
                txn = Transaction()
                goid = shard_oid(oid, s)
                txn.write(goid, 0, data)
                if attrs:
                    txn.setattrs(goid, attrs)
                # a timed-out push is NOT a recovery: reporting it
                # copied would let the steady-state skip strand the
                # shard until an unrelated acting change
                copied = self._push_shard_txn(acting[s],
                                              spg_t(pgid, s), txn)
                if copied:
                    break
            if not copied:
                still_missing.append(s)
        if not still_missing:
            self.pg_ledger.count(pgid, "objects_recovered")
            self.cct.dout("osd", 5,
                          f"backfilled {oid.name} shards {missing} "
                          f"of pg {pgid} by copy")
            return True
        if len(still_missing) > be.m:
            if not unreachable and all(
                    self.osdmap.is_up(o.id)
                    for o in self.osdmap.osds.values()):
                # every holder in the cluster answered and fewer than
                # k shards exist anywhere: the object is UNFOUND — a
                # partial write that never acked, or loss beyond m.
                # Latch it (reference marks unfound rather than
                # retrying forever); a later pass re-evaluates.
                self._unfound.setdefault(pgid, set()).add(oid)
                self.cct.dout("osd", 1,
                              f"{oid.name}: unfound in pg {pgid} "
                              f"({len(still_missing)} shards beyond "
                              f"m={be.m}, all holders answered)")
                return True
            self.cct.dout("osd", 1,
                          f"{oid.name}: {len(still_missing)} shards "
                          f"unrecoverable in pg {pgid}")
            return False
        # 2: reconstruct-from-k via the EC decode path — deferred to
        # the caller's batched pass when one is running (the storm
        # case: one grouped launch rebuilds the whole queue)
        if decode_queue is not None:
            decode_queue.append((oid, still_missing))
            return True     # outcome decided by the batch pass
        try:
            be.recover_shard(
                oid, still_missing,
                self._make_recovery_push(pgid, acting, oid))
            self.pg_ledger.count(pgid, "objects_recovered")
            self.cct.dout("osd", 5,
                          f"recovered {oid.name} shards "
                          f"{still_missing} of pg {pgid} by decode")
            return True
        except Exception as e:  # noqa: BLE001
            import traceback
            self.cct.dout("osd", 1,
                          f"recovery of {oid.name} failed: {e!r}\n" +
                          traceback.format_exc())
            return False

    @staticmethod
    def _obj_ver(attrs) -> tuple[int, int]:
        """Decode a replicated object's "_v" stamp to (epoch, version);
        unstamped legacy copies sort lowest (ties keep the local copy,
        i.e. pre-stamp behavior)."""
        v = (attrs or {}).get("_v")
        if v is None:
            return (0, 0)
        try:
            if isinstance(v, np.ndarray):
                v = v.tobytes()
            elif isinstance(v, str):
                v = v.encode()
            e, _, n = bytes(v).partition(b".")
            return (int(e), int(n))
        except (ValueError, TypeError):
            return (0, 0)

    def _recover_replicated_pg(self, pgid: pg_t,
                               acting: list[int],
                               prevmap=None,
                               unreachable: set | None = None,
                               force: bool = False) -> None:
        from ..store.object_store import Transaction
        pool = self.osdmap.pools.get(pgid.pool)
        prevmap = prevmap if prevmap is not None else self.prev_osdmap
        unreachable = unreachable if unreachable is not None else set()
        fresh_child = False
        prev_acting = None
        if prevmap is not None and pgid.pool in prevmap.pools:
            fresh_child = pgid.seed >= prevmap.pools[pgid.pool].pg_num
            try:
                _, prev_acting, _, _ = \
                    prevmap.pg_to_up_acting_osds(pgid)
                if not force and not fresh_child and \
                        list(prev_acting) == list(acting) and \
                        pgid not in self._pgs_needing_recovery and \
                        all(self.osdmap.is_up(o) for o in acting):
                    return   # steady state: nothing moved
            except Exception:  # noqa: BLE001
                prev_acting = None
        spg = spg_t(pgid, NO_SHARD)
        self.pg_ledger.transition(pgid, "recovering",
                                  epoch=self.osdmap.epoch)
        scan_timer = self.pg_ledger.stage(pgid, "scan")
        scan_timer.__enter__()
        names = self._pg_object_names(pgid, acting, [0],
                                      unreachable=unreachable)
        # union over all replicas so a primary that lost data also heals
        for r, osd in enumerate(acting):
            if osd != self.osd_id and self.osdmap.is_up(osd) and \
                    osd not in unreachable:
                for oj in self._remote_list(osd, spg,
                                            unreachable=unreachable):
                    names.add(M.hobj_from_json(oj))
        # the PG moved: objects may live ONLY on old holders — a full
        # remap (both replicas changed at once, e.g. a drain step)
        # would otherwise strand them, since the new acting set lists
        # nothing.  Ordinary interval changes list just the DEPARTED
        # holders (the replicated analog of the EC shard_moved scan);
        # a retry/fresh-child pass widens to every up OSD.  Listings
        # share the pass's unreachable cache so a dead-but-marked-up
        # peer costs one timeout, not one per PG.
        ancestors = []
        up_osds = [o.id for o in self.osdmap.osds.values()
                   if o.up and o.id not in unreachable]
        wide = fresh_child or pgid in self._pgs_needing_recovery or \
            prev_acting is None
        scan = [o for o in up_osds if o not in acting] if wide else \
            [o for o in prev_acting
             if o not in acting and self.osdmap.is_up(o) and
             o not in unreachable]
        for osd in scan:
            for oj in self._remote_list(osd, spg, timeout=3.0,
                                        unreachable=unreachable):
                names.add(M.hobj_from_json(oj))
        if wide:
            # split child / merge parent: ancestor and dying-child
            # collections of not-yet-swept holders too
            ancestors = self._split_ancestors(pgid) + \
                self._merge_source_pgs(pgid)
            if pool is not None:
                names |= self._names_from_ancestors(
                    pgid, ancestors, [0], pool.pg_num, up_osds,
                    unreachable)
        if pool is not None and pool.pg_num:
            names = {h for h in names
                     if crush_hash32(h.key or h.name) % pool.pg_num ==
                     pgid.seed}
        scan_timer.__exit__(None, None, None)
        self.pg_ledger.count(pgid, "objects_scanned", len(names))
        all_ok = True
        peers = [o for o in acting
                 if o != self.osd_id and self.osdmap.is_up(o) and
                 o not in unreachable]
        for oid in names:
            if self._hb_stop.is_set():
                return
            goid = ghobject_t(oid, shard=NO_SHARD)
            local = None
            try:
                local = (self.store.read(self._cid(spg), goid),
                         self.store.getattrs(self._cid(spg), goid),
                         self.store.omap_get(self._cid(spg), goid),
                         self.store.omap_get_header(self._cid(spg),
                                                    goid))
            except KeyError:
                pass
            # the primary's OWN copy is not authoritative across an
            # interval change: a revived ex-primary holds stale data
            # while the interim primary holds acked writes.  Compare
            # the per-object "_v" stamp (epoch-first, so interim
            # writes beat a dead primary's last epoch) across every
            # acting holder — stat-only probes, the stamp rides the
            # attrs — and adopt the winner BEFORE pushing; pushing
            # blind used to roll acked overwrites back.
            best = local
            best_ver = self._obj_ver(local[1]) if local else None
            best_osd = None
            for osd in peers:
                if osd in unreachable:   # grown mid-pass: one
                    continue             # timeout, not one per object
                got = self._remote_read_full(osd, spg, oid,
                                             want_omap=True,
                                             stat_only=True,
                                             unreachable=unreachable)
                if got is None:
                    continue
                ver = self._obj_ver(got[1])
                if best is None or ver > best_ver:
                    best, best_ver, best_osd = got, ver, osd
            if best_osd is not None:
                # a peer wins: fetch its data (probe carried none)
                full = self._remote_read_full(best_osd, spg, oid,
                                              want_omap=True,
                                              unreachable=unreachable)
                # the winner vanished between probe and read (moved
                # by a split sweep, or its holder died): fall back to
                # the local copy rather than dropping the object
                best = full if full is not None else local
            if best is None:
                # on no acting holder — pull from a pre-split
                # holder's child/ancestor collection
                if not self._pull_replicated_object(
                        pgid, spg, oid, goid, ancestors, up_osds):
                    all_ok = False
                    continue
                try:
                    best = (self.store.read(self._cid(spg), goid),
                            self.store.getattrs(self._cid(spg), goid),
                            self.store.omap_get(self._cid(spg), goid),
                            self.store.omap_get_header(
                                self._cid(spg), goid))
                except KeyError:
                    # a concurrent split/merge sweep moved the object
                    # out of this collection — someone else's to
                    # recover now; keep the pass alive and let the
                    # retry converge
                    all_ok = False
                    continue
            elif best is not local:
                # a peer holds a newer copy: adopt it locally
                # (remove-then-rewrite so stale longer data or stale
                # omap keys cannot survive underneath)
                data, attrs, omap, omap_hdr = best
                txn = Transaction()
                txn.remove(goid)
                txn.touch(goid)
                if np.asarray(data).size:
                    txn.write(goid, 0, np.asarray(data))
                if attrs:
                    txn.setattrs(goid, attrs)
                if omap:
                    txn.omap_setkeys(goid, omap)
                if omap_hdr:
                    txn.omap_setheader(goid, omap_hdr)
                self.apply_shard_txn(spg, txn)
            data, attrs, omap, omap_hdr = best
            oid_ok = True
            for osd in acting:
                if osd == self.osd_id or not self.osdmap.is_up(osd):
                    continue
                txn = Transaction()
                txn.write(goid, 0, data)
                if attrs:
                    txn.setattrs(goid, attrs)
                # full omap sync: clear first so keys/headers deleted
                # on the primary don't survive on a diverged replica
                txn.omap_clear(goid)
                if omap:
                    txn.omap_setkeys(goid, omap)
                if omap_hdr:
                    txn.omap_setheader(goid, omap_hdr)
                with self.pg_ledger.stage(pgid, "push"):
                    pushed = self._push_shard_txn(osd, spg, txn)
                if not pushed:
                    all_ok = False
                    oid_ok = False
            if oid_ok:
                # replicated "recovered" = reconciled: adopted and/or
                # re-pushed to every live replica without a timeout
                self.pg_ledger.count(pgid, "objects_recovered")
        if all_ok:
            self._pgs_needing_recovery.discard(pgid)
            self._note_pg_redundancy(
                pgid, acting,
                pool.size if pool is not None else len(acting))
        else:
            self._pgs_needing_recovery.add(pgid)
            self.pg_ledger.transition(pgid, "recovery_deferred",
                                      epoch=self.osdmap.epoch)
            self.pg_ledger.degraded_open(pgid)

    def _reconcile_replicated_pg(self, pgid: pg_t,
                                 state: PGState) -> bool:
        """Replicated analog of _peer_pg: before a fresh primary
        serves its first op, reconcile every object with the acting
        set so a revived stale ex-primary cannot serve (or RMW over)
        data older than an interim primary's acked writes.  Returns
        True when the PG is consistent enough to serve."""
        _, acting, _, _ = self.osdmap.pg_to_up_acting_osds(pgid)
        try:
            self._recover_replicated_pg(pgid, list(acting), force=True)
        except Exception as e:  # noqa: BLE001
            self.cct.dout("osd", 1,
                          f"replicated reconcile of {pgid} failed: "
                          f"{e!r}")
            return False
        return pgid not in self._pgs_needing_recovery

    def _pull_replicated_object(self, pgid: pg_t, spg: spg_t,
                                oid: hobject_t, goid: ghobject_t,
                                ancestors, up_osds) -> bool:
        """Fetch a whole replicated object (data + xattrs + omap) from
        any up holder into the local primary collection.  Sources are
        the PG's own collection on any OSD, then ancestor collections
        (split holders whose sweep lags)."""
        from ..store.object_store import Transaction
        for src_pg in [pgid] + list(ancestors):
            sspg = spg_t(src_pg, NO_SHARD)
            for osd in up_osds:
                if osd == self.osd_id:
                    continue
                got = self._remote_read_full(osd, sspg, oid,
                                             want_omap=True)
                if got is None:
                    continue
                data, attrs, omap, omap_hdr = got
                txn = Transaction()
                txn.touch(goid)
                if data.size:
                    txn.write(goid, 0, data)
                if attrs:
                    txn.setattrs(goid, attrs)
                if omap:
                    txn.omap_setkeys(goid, omap)
                if omap_hdr:
                    txn.omap_setheader(goid, omap_hdr)
                self.apply_shard_txn(spg, txn)
                self.cct.dout("osd", 5,
                              f"pulled {oid.name} of pg {pgid} from "
                              f"osd.{osd} ({src_pg})")
                return True
        return False

    # -- PG split (reference PG::split_into / OSD::advance_pg splits;
    #    the ps-bits rule: an object's child PG is hash mod new pg_num,
    #    so with power-of-two stepping parent seed s scatters exactly
    #    into {s + i*old_pg_num}) ------------------------------------------

    def _split_pool_collections(self, pool_id: int, new_n: int) -> None:
        """Rehash every local shard collection of a grown pool: objects
        whose ps-bits now select a child PG move — data, xattrs, omap,
        rollback generations, snap clones — together with their PG log
        entries; the child inherits the parent's info bounds.  Runs
        under the split lock so no sub-write can slip an object into a
        parent collection behind the sweep."""
        with self._split_lock:
            for cid in list(self.store.list_collections()):
                if cid.pgid.pool != pool_id or cid.pgid.seed >= new_n:
                    continue
                # parents are every pre-existing seed; a child created
                # moments ago by another pool grow step is covered too
                # (its objects already rehash to themselves)
                try:
                    self._split_shard_collection(cid, new_n)
                except KeyError:
                    continue   # collection raced away (pg removal)

    def _split_shard_collection(self, cid: spg_t, new_n: int) -> None:
        from .pg_log import PG_META_NAME
        parent_seed = cid.pgid.seed
        gobjs = self.store.list_objects(cid)
        moves: dict[int, list[ghobject_t]] = {}
        for g in gobjs:
            if g.hobj.name == PG_META_NAME:
                continue
            seed = crush_hash32(g.hobj.key or g.hobj.name) % new_n
            if seed != parent_seed:
                moves.setdefault(seed, []).append(g)
        if not moves:
            return
        slog = self._shard_log(cid)
        ptxn = Transaction()
        for child_seed, goids in sorted(moves.items()):
            child = spg_t(pg_t(cid.pgid.pool, child_seed), cid.shard)
            ccid = self._cid(child)
            ctxn = Transaction()
            names = {g.hobj.name for g in goids}
            for g in goids:
                self._stage_object_copy(cid, ctxn, g)
                ptxn.remove(g)
            self.store.queue_transactions(ccid, [ctxn])
            # the child's shard log inherits the entries of its objects
            # plus the parent's last_update/les bounds — that history is
            # what lets child peering fence stale shards exactly like a
            # parent interval change would
            moved_entries = [e for e in slog.log.entries
                             if e.oid.name in names]
            self._shard_log(child).merge_split(
                moved_entries, slog.info.last_update,
                slog.info.last_epoch_started)
            # holder-driven delivery: this OSD now owes these objects
            # to the child's acting home (one hobj per name suffices —
            # the pusher copies every ghobject of the name)
            by_name: dict[str, hobject_t] = {}
            for g in goids:
                by_name.setdefault(g.hobj.name, g.hobj)
            self._queue_split_push(child, set(by_name.values()))
            self.cct.dout("osd", 3,
                          f"split {cid}: {len(goids)} shard objects "
                          f"-> {child}")
        slog.split_out({g.hobj.name
                        for gs in moves.values() for g in gs})
        self.store.queue_transactions(cid, [ptxn])

    def _stage_object_copy(self, src_cid: spg_t, txn: Transaction,
                           g: ghobject_t) -> None:
        """Stage one ghobject's full state (data, xattrs, omap) into a
        transaction bound for another collection, same ghobject id."""
        txn.touch(g)
        data = self.store.read(src_cid, g)
        if data.size:
            txn.write(g, 0, data)
        attrs = self.store.getattrs(src_cid, g)
        if attrs:
            txn.setattrs(g, attrs)
        try:
            omap = self.store.omap_get(src_cid, g)
            hdr = self.store.omap_get_header(src_cid, g)
        except KeyError:
            omap, hdr = {}, b""
        if omap:
            txn.omap_setkeys(g, omap)
        if hdr:
            txn.omap_setheader(g, hdr)

    # -- PG merge (the inverse of the split sweep; reference
    #    PG::merge_from / OSDMonitor pg_num decrease, Nautilus) ------------

    def _merge_pool_collections(self, pool_id: int, new_n: int) -> None:
        """Fold every local shard collection whose seed the shrunk
        pg_num no longer covers into its parent (seed mod new_n):
        data, xattrs, omap, rollback generations and snap clones move,
        the child's shard log unions into the parent's WITHOUT moving
        its peering bounds (`ShardPGLog.fold_in` explains why), and
        the folded objects queue for holder-driven delivery to the
        parent's acting home.  Runs under the split lock — a concurrent
        sub-write must not land in a child collection behind the
        fold."""
        with self._split_lock:
            for cid in list(self.store.list_collections()):
                if cid.pgid.pool != pool_id or cid.pgid.seed < new_n:
                    continue
                try:
                    self._merge_shard_collection(cid, new_n)
                except KeyError:
                    continue   # collection raced away

    def _merge_shard_collection(self, cid: spg_t, new_n: int) -> None:
        from .pg_log import PG_META_NAME
        parent = spg_t(pg_t(cid.pgid.pool, cid.pgid.seed % new_n),
                       cid.shard)
        gobjs = [g for g in self.store.list_objects(cid)
                 if g.hobj.name != PG_META_NAME]
        slog = self._shard_log(cid)
        if gobjs:
            pcid = self._cid(parent)
            ctxn = Transaction()
            by_name: dict[str, hobject_t] = {}
            for g in gobjs:
                self._stage_object_copy(cid, ctxn, g)
                by_name.setdefault(g.hobj.name, g.hobj)
            self.store.queue_transactions(pcid, [ctxn])
            # this OSD now owes the folded objects to the parent's
            # acting home under the new map — same holder-driven
            # delivery as a split (_queue_split_push pushes from
            # whatever collection the target pgid names)
            self._queue_split_push(parent, set(by_name.values()))
            self.cct.dout("osd", 3,
                          f"merge {cid}: {len(gobjs)} shard objects "
                          f"-> {parent}")
        # log union, bounds-preserving (see ShardPGLog.fold_in for why
        # the parent's peering bounds must not ratchet): child entries
        # above the bound travel as unlogged backfill data instead
        # (push + wide recovery scan), the proven split-push path
        self._shard_log(parent).fold_in(list(slog.log.entries))
        # the child is dead: drop its collection and log state so a
        # later re-grow starts from a clean slate
        with self.pg_lock:
            self.shard_logs.pop(cid, None)
        try:
            self.store.remove_collection(cid)
        except KeyError:
            pass
        self._created_cids.discard(cid)

    def _is_dying_pg(self, pgid: pg_t) -> bool:
        """A merge child the current map has folded away: its seed is
        beyond the pool's pg_num but within the committed historical
        maximum (OSDMap pg_num_max) — derivable on ANY osd, including
        one that slept through the shrink."""
        pool = self.osdmap.pools.get(pgid.pool)
        return pool is not None and \
            pool.pg_num <= pgid.seed < pool.pg_num_ever()

    def _merge_source_pgs(self, pgid: pg_t) -> list[pg_t]:
        """Dying children (across stacked shrinks too: every retired
        seed congruent to pgid mod pg_num) that fold into pgid — the
        collections recovery/reads consult while a merge settles.
        Map-derived, so it survives reboots."""
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None or not pool.pg_num or \
                pgid.seed >= pool.pg_num:
            return []
        return [pg_t(pgid.pool, s)
                for s in range(pgid.seed + pool.pg_num,
                               pool.pg_num_ever(), pool.pg_num)]

    @staticmethod
    def _txn_hobjs(txn: Transaction) -> set[hobject_t]:
        out: set[hobject_t] = set()
        for op in txn.ops:
            for attr in ("oid", "src", "dst"):
                goid = getattr(op, attr, None)
                if goid is not None:
                    out.add(goid.hobj)
        return out

    def _migrate_misplaced(self, spg: spg_t,
                           hobjs: set[hobject_t]) -> None:
        """Post-apply split routing for writes that raced a pg_num
        grow: a sub-write issued against the parent PG by a primary on
        the old map applies verbatim (log append included), then any
        object that rehashes into a child under THIS osd's map moves
        immediately.  Caller holds the split lock."""
        from .pg_log import PG_META_NAME
        pool = self.osdmap.pools.get(spg.pgid.pool)
        if pool is None or not pool.pg_num:
            return
        for hobj in hobjs:
            if hobj.name == PG_META_NAME:
                continue
            seed = crush_hash32(hobj.key or hobj.name) % pool.pg_num
            if seed == spg.pgid.seed:
                continue
            if spg.pgid.seed >= pool.pg_num and \
                    not self._is_dying_pg(spg.pgid):
                # WE are behind the writer's map (a child sub-write
                # arriving before our split sweep): leave it — our own
                # sweep re-homes everything when the new map lands.
                # (A recorded merge ancestor means the opposite: the
                # WRITER is behind and this child is dying — fall
                # through and fold the write into the parent now.)
                continue
            cid = self._cid(spg)
            child = spg_t(pg_t(spg.pgid.pool, seed), spg.shard)
            ccid = self._cid(child)
            goids = [g for g in self.store.list_objects(cid)
                     if g.hobj.name == hobj.name]
            if not goids:
                continue
            ctxn = Transaction()
            for g in goids:
                self._stage_object_copy(cid, ctxn, g)
            self.store.queue_transactions(ccid, [ctxn])
            slog = self._shard_log(spg)
            moved = slog.split_out({hobj.name})
            if self._is_dying_pg(spg.pgid):
                # merge direction (dying child -> parent): bounds-
                # preserving fold — the write's data still travels
                # via the push below
                self._shard_log(child).fold_in(moved)
            else:
                # split direction: the child inherits the parent's
                # bounds (uniform across holders — every parent
                # shard's log carries the same lineage)
                self._shard_log(child).merge_split(
                    moved, slog.info.last_update,
                    slog.info.last_epoch_started)
            ptxn = Transaction()
            for g in goids:
                ptxn.remove(g)
            self.store.queue_transactions(cid, [ptxn])
            # a write acked through the OLD primary after the child
            # primary's recovery pass already ran has no other way to
            # reach the child's acting home — the holder delivers it
            self._queue_split_push(child, {hobj})

    def _queue_split_push(self, child: spg_t,
                          hobjs: set[hobject_t]) -> None:
        """Remember objects this OSD re-homed into a child collection
        until they are confirmed on the child's acting home, and arm
        the pusher."""
        from .pg_log import PG_META_NAME
        with self.pg_lock:
            for h in hobjs:
                if h.name != PG_META_NAME:
                    self._split_push_pending.add((child, h))
            if not self._split_push_pending or self._split_pusher_armed:
                return
            self._split_pusher_armed = True
        t = threading.Timer(0.2, self._drain_split_pushes)
        t.daemon = True
        t.start()

    def _drain_split_pushes(self) -> None:
        """Deliver locally re-homed split objects to the child's
        acting set; whatever cannot land yet (target down, acting
        hole) retries on a timer until the queue drains."""
        if self._hb_stop.is_set():
            with self.pg_lock:
                self._split_pusher_armed = False
            return
        with self.pg_lock:
            pending = list(self._split_push_pending)
        for child, hobj in pending:
            if self._hb_stop.is_set():
                break
            try:
                done = self._push_split_object(child, hobj)
            except Exception:  # noqa: BLE001 - keep the queue alive
                done = False
            if done:
                with self.pg_lock:
                    self._split_push_pending.discard((child, hobj))
        with self.pg_lock:
            more = bool(self._split_push_pending) and \
                not self._hb_stop.is_set()
            if not more:
                self._split_pusher_armed = False
        if more:
            t = threading.Timer(2.0, self._drain_split_pushes)
            t.daemon = True
            t.start()

    def _push_split_object(self, child: spg_t, hobj: hobject_t) -> bool:
        """Copy one re-homed object (all its ghobjects) from the local
        child collection to where the child PG actually lives under
        the CURRENT map.  EC: this OSD held shard `child.shard` of the
        parent, so exactly the same shard of the child is its to
        deliver.  Replicated: the full object goes to every acting
        replica.  True = nothing left to deliver."""
        from ..crush.map import CRUSH_ITEM_NONE
        pool = self.osdmap.pools.get(child.pgid.pool)
        if pool is None or child.pgid.seed >= pool.pg_num:
            if pool is not None and self._is_dying_pg(child.pgid):
                # the target child died in a merge: the fold sweep
                # moved its objects to the parent and queued parent
                # pushes — this entry is superseded, not stuck
                return True
            return pool is None   # pool gone: drop; map lag: retry
        cid = self._cid(child)
        goids = [g for g in self.store.list_objects(cid)
                 if g.hobj.name == hobj.name]
        if not goids:
            return True           # deleted / re-homed again meanwhile
        try:
            _, acting, _, _ = self.osdmap.pg_to_up_acting_osds(
                child.pgid)
        except Exception:  # noqa: BLE001 - unmapped pg: retry later
            return False
        if pool.is_erasure():
            s = child.shard
            if s < 0 or s >= len(acting):
                return True       # shard position no longer exists
            tgt = acting[s]
            if tgt == CRUSH_ITEM_NONE or not self.osdmap.is_up(tgt):
                return False      # hole/down: retry when it heals
            targets = [tgt]
        else:
            targets = [o for o in acting if o != CRUSH_ITEM_NONE and
                       self.osdmap.is_up(o)]
            if len(targets) < len(acting) or not targets:
                return False      # push to the FULL set or retry
        ok_all = True
        for tgt in targets:
            if tgt == self.osd_id:
                continue          # already local
            txn = Transaction()
            for g in goids:
                self._stage_object_copy(cid, txn, g)
            if not self._push_shard_txn(tgt, child, txn, timeout=10.0):
                ok_all = False
        return ok_all

    def _fallback_spgs(self, spg: spg_t,
                       oid: hobject_t | None = None) -> list[spg_t]:
        """Where a shard object may still live while a split or merge
        settles, in probe order: the recorded split parent (this OSD
        already split), the seed the LOCAL pg_num folds the request
        to (this OSD's map predates the child entirely), the seed the
        OBJECT hashes to under the local pg_num (this OSD's map
        predates a merge — the object still sits in the old child
        collection), and any recorded dying merge children of the
        requested PG (local fold pending or mid-flight)."""
        out: list[spg_t] = []

        def add(pg: pg_t) -> None:
            cand = spg_t(pg, spg.shard)
            if cand != spg and cand not in out:
                out.append(cand)

        anc = self._split_ancestry.get(spg.pgid)
        if anc is not None:
            add(anc)
        pool = self.osdmap.pools.get(spg.pgid.pool)
        if pool is not None and pool.pg_num:
            if spg.pgid.seed >= pool.pg_num:
                add(pg_t(spg.pgid.pool,
                         spg.pgid.seed % pool.pg_num))
            if oid is not None:
                add(pg_t(spg.pgid.pool,
                         crush_hash32(oid.key or oid.name) %
                         pool.pg_num))
        for child in self._merge_source_pgs(spg.pgid):
            add(child)
        return out

    def _split_ancestors(self, pgid: pg_t) -> list[pg_t]:
        """The ancestry chain of a child PG (oldest last), empty for
        PGs that never split out."""
        out: list[pg_t] = []
        cur = self._split_ancestry.get(pgid)
        while cur is not None and cur not in out and cur != pgid:
            out.append(cur)
            cur = self._split_ancestry.get(cur)
        return out

    # -- shard-side ops (any OSD) ------------------------------------------

    def _cid(self, spg: spg_t) -> spg_t:
        if spg not in self._created_cids:
            self.store.create_collection(spg)
            self._created_cids.add(spg)
        return spg

    def apply_shard_txn(self, spg: spg_t, txn: Transaction) -> None:
        with self._split_lock:
            self.store.queue_transactions(self._cid(spg), [txn])
            self._migrate_misplaced(spg, self._txn_hobjs(txn))

    def _shard_log(self, spg: spg_t):
        from .pg_log import ShardPGLog
        with self.pg_lock:
            slog = self.shard_logs.get(spg)
            if slog is None:
                slog = self.shard_logs[spg] = ShardPGLog(
                    self.store, self._cid(spg), spg.shard)
            return slog

    def apply_sub_write(self, spg: spg_t, txn: Transaction,
                        wire_entries: list, at_version: eversion_t,
                        rollforward_to: eversion_t | None) -> None:
        """Shard write + atomic log persistence (reference
        ECBackend::handle_sub_write, ECBackend.cc:915: the log entries
        ride the same ObjectStore transaction as the data)."""
        from .pg_log import entry_from_wire
        if not wire_entries:
            self.apply_shard_txn(spg, txn)
            return
        entries = [entry_from_wire(w) for w in wire_entries]
        with self._split_lock:
            slog = self._shard_log(spg)
            slog.append_to_txn(txn, entries, at_version)
            self.store.queue_transactions(self._cid(spg), [txn])
            slog.record(entries, at_version)
            from .ec_util import refresh_chunk_crcs
            refresh_chunk_crcs(self.store, self._cid(spg), spg.shard,
                               entries)
            if rollforward_to is not None:
                slog.advance_rollforward(rollforward_to)
            self._migrate_misplaced(spg, {e.oid for e in entries})

    def _handle_activate(self, msg: M.MPGActivate) -> None:
        from .pg_log import entry_from_wire
        slog = self._shard_log(msg.pgid)
        if msg.adopt:
            slog.adopt([entry_from_wire(w) for w in msg.entries],
                       msg.head, msg.les)
        else:
            slog.set_les(msg.les)

    def read_shard(self, spg: spg_t, oid: hobject_t, off: int,
                   length: int) -> np.ndarray | None:
        goid = ghobject_t(oid, shard=spg.shard)
        try:
            data = self.store.read(self._cid(spg), goid, off,
                                   None if length < 0 else length)
        except KeyError:
            # split/merge settling: the object may still sit in a
            # parent or dying-child collection (local sweep pending,
            # or this OSD's map is older than the requester's)
            data = None
            for fb in self._fallback_spgs(spg, oid):
                if not self.store.collection_exists(fb):
                    continue
                try:
                    data = self.store.read(
                        fb, goid, off,
                        None if length < 0 else length)
                    break
                except KeyError:
                    continue
            if data is None:
                return None
        if length > 0 and data.size < length:
            data = np.concatenate(
                [data, np.zeros(length - data.size, dtype=np.uint8)])
        return data

    def _read_reply(self, spg, oid, off, length) -> M.MOSDECSubOpReadReply:
        data = self.read_shard(spg, oid, off, length)
        if data is None:
            return M.MOSDECSubOpReadReply(spg, 0, spg.shard, -errno.ENOENT)
        return M.MOSDECSubOpReadReply(spg, 0, spg.shard, 0, data.tobytes())

    def stat_shard(self, spg, oid, want_attrs,
                   want_omap: bool = False) -> M.MOSDECSubOpReadReply:
        goid = ghobject_t(oid, shard=spg.shard)
        cid = self._cid(spg)
        try:
            size = self.store.stat(cid, goid)
        except KeyError:
            size = None
            for fb in self._fallback_spgs(spg, oid):  # resize settling
                if not self.store.collection_exists(fb):
                    continue
                try:
                    size = self.store.stat(fb, goid)
                    cid = fb
                    break
                except KeyError:
                    continue
            if size is None:
                return M.MOSDECSubOpReadReply(
                    spg, 0, spg.shard, -errno.ENOENT)
        attrs = self.store.getattrs(cid, goid) if want_attrs else {}
        omap: dict = {}
        omap_hdr = b""
        if want_omap:
            try:
                omap = self.store.omap_get(cid, goid)
                omap_hdr = self.store.omap_get_header(cid, goid)
            except KeyError:
                pass
        return M.MOSDECSubOpReadReply(spg, 0, spg.shard, 0, b"",
                                      attrs, size, omap=omap,
                                      omap_header=omap_hdr)

    def _route_write_reply(self, msg) -> None:
        waiter = self.raw_write_waiters.pop((msg.pgid, msg.tid), None)
        if waiter is not None:
            waiter(msg)
            return
        with self.pg_lock:
            state = self.pgs.get(msg.pgid.pgid)
        if state is None:
            return
        be = state.backend
        tgt = be.shards if state.kind == "ec" else be.replicas
        tgt.handle_write_reply(msg)

    def _route_read_reply(self, msg) -> None:
        waiter = self.raw_read_waiters.pop((msg.pgid, msg.tid), None)
        if waiter is not None:
            waiter(msg)
            return
        with self.pg_lock:
            state = self.pgs.get(msg.pgid.pgid)
        if state is not None and state.kind == "ec":
            state.backend.shards.handle_read_reply(msg)

    # -- primary-side client ops -------------------------------------------

    def _get_pg(self, pgid: pg_t) -> PGState:
        with self.pg_lock:
            state = self.pgs.get(pgid)
            if state is None:
                pool = self.osdmap.pools[pgid.pool]
                up, acting, _, primary = \
                    self.osdmap.pg_to_up_acting_osds(pgid)
                if primary != self.osd_id:
                    raise ErasureCodeError(
                        errno.EAGAIN,
                        f"not primary for {pgid} (is {primary})")
                if pool.is_erasure():
                    prof = self.osdmap.ec_profiles[
                        pool.erasure_code_profile]
                    codec = ErasureCodePluginRegistry.instance().factory(
                        prof["plugin"], Profile(dict(prof)))
                    k = codec.get_data_chunk_count()
                    sinfo = StripeInfo(pool.stripe_width,
                                       pool.stripe_width // k)
                    shards = MessengerShardBackend(self, pgid, acting)
                    backend = ECBackend(
                        codec, sinfo, shards,
                        mesh_service=self._mesh_service(),
                        launch_queue=self._host_launch_queue(),
                        dispatch_depth=int(self.cct.conf.get(
                            "ec_dispatch_ahead_depth") or 2),
                        perf_name=f"ec.{pgid}",
                        logger=lambda msg: self.cct.dout(
                            "osd", 1, msg),
                        read_timeout=float(self.cct.conf.get(
                            "osd_ec_read_timeout") or 30.0),
                        clay_repair=bool(self.cct.conf.get(
                            "osd_ec_clay_repair")))
                    # surface the backend's pipeline counters in this
                    # daemon's `perf dump` / prometheus scrape
                    self.cct.perf.add(backend.perf)
                    if bool(self.cct.conf.get("ec_dispatch_ahead")):
                        backend.set_pipelined(float(self.cct.conf.get(
                            "ec_dispatch_flush_ms") or 2.0))
                    state = PGState(backend, "ec")
                else:
                    replicas = MessengerReplicaBackend(self, pgid, acting)
                    backend = ReplicatedBackend(replicas)
                    state = PGState(backend, "replicated")
                self.pgs[pgid] = state
        # Peer outside pg_lock: the shard-log RPCs must not stall every
        # other PG's dispatch (reference peering happens in its own
        # state machine, ops wait on Active).  EC PGs reconcile shard
        # logs; replicated PGs reconcile object versions — without it a
        # revived stale ex-primary serves (and RMWs over) data older
        # than the interim primary's acked writes before background
        # recovery gets to the PG.
        if state.needs_peer:
            with state.peer_lock:
                if state.needs_peer:
                    # incomplete peering (a live shard didn't answer)
                    # keeps needs_peer set: the next op retries until
                    # every live shard's log has been reconciled
                    self.pg_ledger.transition(
                        pgid,
                        "peering" if state.kind == "ec"
                        else "reconcile",
                        epoch=self.osdmap.epoch)
                    with self.pg_ledger.stage(pgid, "peering"):
                        ok = self._peer_pg(pgid, state) \
                            if state.kind == "ec" else \
                            self._reconcile_replicated_pg(pgid, state)
                    state.needs_peer = not ok
                    self.pg_ledger.transition(
                        pgid, "active" if ok else "peering_incomplete",
                        epoch=self.osdmap.epoch)
            if state.needs_peer:
                # Never serve ops from an unpeered PG: a partial view
                # could miss acked writes held by the silent shard.
                raise ErasureCodeError(
                    errno.EAGAIN,
                    f"pg {pgid} peering incomplete; retry")
        return state

    # -- peering (reference PeeringState.cc GetInfo/GetLog/Activate:
    #    collect shard logs, pick the authoritative one, reconcile) ---------

    def _peer_rpc(self, osd: int, spg: spg_t, msg_cls,
                  timeout: float = 5.0, **kw):
        """One synchronous peering RPC to a remote shard; None on
        timeout/unreachable (the shard is then treated as down)."""
        with self.pg_lock:
            self._raw_tid += 1
            tid = self._raw_tid
        box: dict = {}
        ev = threading.Event()
        self.peer_waiters[(spg, tid)] = \
            lambda m: (box.update(msg=m), ev.set())
        try:
            self.conn_to_osd(osd).send_message(msg_cls(spg, tid, **kw))
        except Exception:  # noqa: BLE001
            self.peer_waiters.pop((spg, tid), None)
            return None
        if not ev.wait(timeout):
            self.peer_waiters.pop((spg, tid), None)
            return None
        return box.get("msg")

    def _peer_pg(self, pgid: pg_t, state: PGState) -> bool:
        """Authoritative-log peering for one EC PG this OSD now leads.
        Returns False when a live shard could not be reconciled (the
        caller must retry before trusting the PG).

        1. GetLog: every live shard reports (pg_info, log entries).
        2. Shards that missed an interval (last_epoch_started below the
           max) are STALE: they don't vote — their data is healed by
           recovery, their history by adoption.
        3. Among current shards the authoritative head is the MINIMUM
           last_update: an acked write committed on every live shard,
           so min >= every acked version; anything above min is an
           unacked partial write.  (reference PeeringState::calc_acting
           + the EC min-on-acting rule.)
        4. Divergent shards roll back locally; objects whose rollback
           state can't undo (deletes, overwrites pre-generations) are
           removed and reconstructed from the authoritative shards.
        5. Activate: everyone persists last_epoch_started = this epoch.
        """
        from ..crush.map import CRUSH_ITEM_NONE
        from .pg_log import (LogEntry, PGLog, entry_from_wire,
                             entry_to_wire, pg_info_t)
        be = state.backend
        acting = be.shards.acting
        live = {s: osd for s, osd in enumerate(acting)
                if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)}
        replies: dict[int, tuple] = {}   # shard -> (pg_info_t, [LogEntry])
        for s, osd in live.items():
            spg = spg_t(pgid, s)
            if osd == self.osd_id:
                slog = self._shard_log(spg)
                replies[s] = (slog.info, list(slog.log.entries))
            else:
                m = self._peer_rpc(osd, spg, M.MPGLogQuery)
                if m is not None:
                    replies[s] = (pg_info_t.from_json(m.info),
                                  [entry_from_wire(w) for w in m.entries])
        complete = set(replies) == set(live)
        if not complete:
            self.cct.dout("osd", 2,
                          f"peering {pgid} incomplete: shards "
                          f"{sorted(set(live) - set(replies))} did "
                          f"not answer")
            # A live shard didn't answer.  Its log may hold acked writes
            # newer than anything we heard; rolling back / activating on
            # the partial view could elect a stale shard as authority and
            # lose acknowledged data.  Do nothing destructive — the caller
            # keeps needs_peer set and refuses ops until a full round
            # succeeds (reference PeeringState only activates after a
            # complete GetInfo/GetLog round).
            return False
        max_les = max(info.last_epoch_started for info, _ in
                      replies.values())
        current = {s for s, (info, _) in replies.items()
                   if info.last_epoch_started == max_les}
        auth_head = min(replies[s][0].last_update for s in current)
        donor = max(current, key=lambda s: replies[s][0].last_update)
        auth_entries = [e for e in replies[donor][1]
                        if e.version <= auth_head]
        if any(replies[s][0].last_update > auth_head for s in replies) \
                or any(s not in current for s in replies):
            self.cct.dout("osd", 3,
                          f"peering {pgid}: auth_head={auth_head} "
                          f"current={sorted(current)} "
                          f"live={sorted(replies)}")
        # 4: divergent rollback
        removed: list[hobject_t] = []
        for s, (info, _) in replies.items():
            if info.last_update <= auth_head:
                continue
            spg = spg_t(pgid, s)
            if live[s] == self.osd_id:
                removed.extend(self._shard_log(spg).rollback_to(auth_head))
            else:
                m = self._peer_rpc(live[s], spg, M.MPGLogRollback,
                                   v=auth_head)
                if m is not None:
                    removed.extend(M.hobj_from_json(j)
                                   for j in m.removed)
        # 5: activate (stale shards adopt the authoritative log)
        les_new = self.osdmap.epoch
        wire_auth = [entry_to_wire(e) for e in auth_entries]
        for s in replies:
            spg = spg_t(pgid, s)
            adopt = s not in current
            if live[s] == self.osd_id:
                self._handle_activate(M.MPGActivate(
                    spg, 0, les_new, auth_head, wire_auth, adopt))
            else:
                self._peer_rpc(live[s], spg, M.MPGActivate, les=les_new,
                               head=auth_head, entries=wire_auth,
                               adopt=adopt)
        # seed the primary's in-memory log + version counter
        newlog = PGLog()
        for e in sorted(auth_entries, key=lambda e: e.version):
            newlog.add(e)
        newlog.head = max(newlog.head, auth_head)
        newlog.can_rollback_to = auth_head
        newlog.rollforward_to = auth_head
        be.log = newlog
        with state.lock:
            state.version = max(state.version, auth_head.version)
        # reconstruct objects whose divergent entries weren't locally
        # rollbackable (authoritative shards never applied them, so
        # decode-from-k yields the pre-divergence object)
        for oid in dict.fromkeys(removed):
            missing = [s for s in live
                       if be.shards.stat(s, oid) is None]
            if not missing:
                continue
            try:
                be.recover_shard(
                    oid, missing,
                    self._make_recovery_push(pgid, acting, oid))
            except Exception as e:  # noqa: BLE001
                self.cct.dout("osd", 1,
                              f"post-peering recovery of {oid.name} "
                              f"failed: {e!r}")
        return complete

    WRITE_OPS = {"write", "writefull", "append", "zero", "create",
                 "truncate", "delete", "setxattr", "rmxattr",
                 "call", "notify", "watch", "unwatch",
                 "omapsetkeys", "omaprmkeys", "omapclear",
                 "omapsetheader"}

    @staticmethod
    def _caps_can_write(caps: str) -> bool:
        """'allow *' or any allow grant containing w ('allow w',
        'allow rw', 'allow rwx' — the OSDCap spellings the keyring
        writes)."""
        import re
        return "allow *" in caps or \
            re.search(r"allow\s+[rx]*w", caps) is not None

    def _reply_op_error(self, conn, msg: M.MOSDOp, e: BaseException
                        ) -> None:
        """Map an op-path exception to an errno reply: ValueError
        (malformed/hostile client payload) becomes EINVAL.  Log only
        the unexpected — EAGAIN is routine peering backoff, and a
        ValueError is already answered, so neither deserves a
        traceback a hostile client could spam."""
        eno = getattr(e, "errno", None) or \
            (errno.EINVAL if isinstance(e, ValueError) else errno.EIO)
        if eno != errno.EAGAIN and not isinstance(e, ValueError):
            import traceback
            traceback.print_exc()
        top = getattr(msg, "top", None)
        if top is not None:
            top.mark_event("failed")
            self.op_tracker.unregister(top, -eno)
        try:
            conn.send_message(M.MOSDOpReply(msg.tid, -eno))
        except Exception:   # connection already gone
            pass

    def _handle_client_op_safe(self, conn, msg: M.MOSDOp) -> None:
        """Exception fence for ops that run off the dispatch thread
        (op pool / notify thread).  Without it a raised error — incl.
        the routine EAGAIN from _get_pg during peering — dies inside
        the Future and the client stalls a full attempt timeout
        instead of fast-retrying (reference: do_op replies -errno on
        every failure path)."""
        top = getattr(msg, "top", NULL_TRACKED)
        top.mark_event("dequeued")
        try:
            self._handle_client_op(conn, msg)
        except Exception as e:  # noqa: BLE001 - must reply, not die
            self._reply_op_error(conn, msg, e)
        finally:
            # idempotent: the write/read paths unregister with their
            # result; this net catches early-return paths (snap
            # reads, watch control ops, caps/blacklist rejections)
            self.op_tracker.unregister(top)

    def _handle_client_op(self, conn, msg: M.MOSDOp) -> None:
        """reference PrimaryLogPG::do_op/do_osd_ops: decode the op
        vector, build a PGTransaction for mutations, execute reads."""
        # blacklist fence (reference OSDMap blacklist / EBLACKLISTED):
        # a fenced client's ops — including ones already in flight
        # when an exclusive-lock steal blacklisted it — are rejected,
        # never applied
        ent = getattr(conn, "peer_entity", None)
        if ent is not None and \
                self.osdmap.blacklist.get(ent, 0) > time.time():
            # expired entries no longer fence (the mon prunes them
            # from the map lazily; the TTL is authoritative here)
            conn.send_message(M.MOSDOpReply(
                msg.tid, -errno.ESHUTDOWN, b"", self.osdmap.epoch))
            return
        # OSDCap check: a read-only client credential cannot mutate
        # (reference OSDCap grammar reduced to the keyring's subset)
        if self.messenger.auth is not None:
            ident = getattr(conn.session, "auth_identity", None) or {}
            caps = ident.get("caps", "")
            if ident.get("kind") in ("ticket", "client_key") and \
                    not self._caps_can_write(caps) and \
                    any(op[0] in self.WRITE_OPS for op in msg.ops):
                conn.send_message(M.MOSDOpReply(
                    msg.tid, -errno.EACCES, b"", self.osdmap.epoch))
                return
        self.perf.inc("op")
        _t0 = time.perf_counter()
        # Per-object op ordering (reference PrimaryLogPG do_op obc
        # ordering): ALL ops on one object serialize — cls calls are
        # read-modify-write and must not interleave with each other OR
        # with plain writes.  Striped locks keep the table bounded.
        # Watch/notify control ops stay lock-free: notify blocks on
        # watcher acks, and a watcher that touches the object in its
        # handler would deadlock against the stripe (the reference
        # drops the obc lock around the ack wait too).
        if {op[0] for op in msg.ops} <= {"watch", "unwatch", "notify"}:
            if any(op[0] == "notify" for op in msg.ops):
                # notify blocks on watcher acks; a watcher sharing the
                # notifier's connection would ack on the very reader
                # thread this handler is occupying — run async
                # (reference: notifies complete via a Context, not
                # inline in the dispatch thread)
                threading.Thread(
                    target=self._do_client_op_safe, args=(conn, msg, _t0),
                    daemon=True,
                    name=f"osd.{self.osd_id}.notify").start()
            else:
                self._do_client_op(conn, msg, _t0)
            return
        key = (msg.pgid.pgid.pool, msg.oid.name)
        with self._obj_locks[hash(key) % len(self._obj_locks)]:
            self._do_client_op(conn, msg, _t0)

    def _do_client_op_safe(self, conn, msg: M.MOSDOp, _t0: float) -> None:
        """Same exception fence as _handle_client_op_safe for the
        detached notify thread."""
        try:
            self._do_client_op(conn, msg, _t0)
        except Exception as e:  # noqa: BLE001
            self._reply_op_error(conn, msg, e)

    def _do_client_op(self, conn, msg: M.MOSDOp, _t0: float) -> None:
        # PG-split retarget (reference OSD::handle_op split requeue):
        # under THIS osd's map the object may hash into a child of the
        # PG the client computed.  If we lead the child, the op simply
        # requeues against it; otherwise _get_pg raises EAGAIN and the
        # client retargets off its refreshed map.
        top = getattr(msg, "top", NULL_TRACKED)
        pool = self.osdmap.pools.get(msg.pgid.pgid.pool)
        if pool is not None and pool.pg_num:
            actual = self.osdmap.object_to_pg(pool.id, msg.oid.name,
                                              msg.oid.key)
            if actual != msg.pgid.pgid:
                msg.pgid = spg_t(actual, msg.pgid.shard)
                top.set_info("pg", str(msg.pgid.pgid))
        state = self._get_pg(msg.pgid.pgid)
        be = state.backend
        if msg.oid.snap != 0:
            self._do_snap_read(conn, msg, state)
            return
        txn = PGTransaction()
        data_off = 0
        read_payload = b""
        result = 0
        out_meta: list = []
        # op-vector OVERLAY: later ops in one compound message must see
        # the staged effects of earlier ones (reference do_osd_ops runs
        # the vector against the evolving object state).  vsize/vexists
        # None = not yet consulted; vattrs holds staged xattr values
        # (None = staged removal).
        vsize: int | None = None
        vexists: bool | None = None
        vattrs: dict[str, bytes | None] = {}
        vtrunc: int | None = None        # staged truncate_to (the txn
        # holds ONE truncate value applied after writes, so an op that
        # extends past it must raise it or be clipped)
        vbase_dropped = False            # a delete ran in this vector:
        # the committed object state (size, xattrs) is gone for good,
        # even if a later op recreates the object — consult only the
        # staged views from then on

        def cur_exists() -> bool:
            nonlocal vexists
            if vexists is None:
                vexists = self._object_exists(state, msg.oid)
            return vexists

        def cur_size():
            nonlocal vsize, vexists
            if vexists is False:
                # known absent (a staged delete/earlier miss), which is
                # DISTINCT from vsize None = "not yet consulted": the op
                # vector must see the evolving state, not re-read the
                # committed pre-delete object (reference do_osd_ops runs
                # later ops against the mutated obs)
                return None
            if vsize is None:
                if vbase_dropped:
                    return None  # recreated post-delete but size never
                    # staged: committed state is dead, nothing to read
                vsize = self._stat_logical(state, msg.oid)
                vexists = vsize is not None
            return vsize

        def cur_xattr(key: str):
            if key in vattrs:
                return vattrs[key]
            if vexists is False or vbase_dropped:
                # known absent OR recreated after an in-vector delete:
                # the committed xattrs died with the delete — a
                # fall-through read would resurrect pre-delete values
                return None
            from ..cls import ClsContext
            ctx = ClsContext(self, state, msg.pgid.pgid, msg.oid)
            return ctx.getxattr(key)

        for op in msg.ops:
            name = op[0]
            if name == "write":
                _, off, ln = op
                txn.write(msg.oid, off,
                          np.frombuffer(msg.data[data_off:data_off + ln],
                                        dtype=np.uint8))
                data_off += ln
                vsize = max(cur_size() or 0, off + ln)
                vexists = True
                if vtrunc is not None and off + ln > vtrunc:
                    txn.truncate(msg.oid, off + ln)
                    vtrunc = off + ln
            elif name == "writefull":
                _, ln = op
                txn.write(msg.oid, 0,
                          np.frombuffer(msg.data[data_off:data_off + ln],
                                        dtype=np.uint8))
                txn.truncate(msg.oid, ln)  # clip any previous tail
                data_off += ln
                vsize, vexists, vtrunc = ln, True, ln
            elif name == "truncate":
                txn.truncate(msg.oid, op[1])
                vsize = vtrunc = op[1]
            elif name == "append":
                # reference CEPH_OSD_OP_APPEND: write at current size
                _, ln = op
                size = cur_size() or 0
                txn.write(msg.oid, size,
                          np.frombuffer(msg.data[data_off:data_off + ln],
                                        dtype=np.uint8))
                data_off += ln
                vsize, vexists = size + ln, True
                if vtrunc is not None and size + ln > vtrunc:
                    txn.truncate(msg.oid, size + ln)
                    vtrunc = size + ln
            elif name == "zero":
                # reference CEPH_OSD_OP_ZERO: logical zeros, no size
                # change; on a nonexistent object it is a successful
                # no-op (PrimaryLogPG ZERO: !obs.exists -> result 0)
                _, off, ln = op
                size = cur_size()
                if size is not None and off < size:
                    txn.write(msg.oid, off,
                              np.zeros(min(ln, size - off),
                                       dtype=np.uint8))
            elif name == "create":
                # reference CEPH_OSD_OP_CREATE: op[1] truthy = excl
                if cur_exists():
                    if len(op) > 1 and op[1]:
                        result = -errno.EEXIST
                        break
                else:
                    txn.write(msg.oid, 0,
                              np.zeros(0, dtype=np.uint8))
                    vsize, vexists = 0, True
            elif name == "delete":
                txn.delete(msg.oid)
                vsize, vexists, vattrs = None, False, {}
                vbase_dropped = True
            elif name == "rmxattr":
                # reference: rmxattr on a nonexistent object is ENOENT
                # (it must not materialize a phantom object)
                if not cur_exists():
                    result = -errno.ENOENT
                    break
                txn.setattr(msg.oid, op[1], None)
                vattrs[op[1]] = None
            elif name == "getxattr":
                val = cur_xattr(op[1])
                if val is None:
                    result = -errno.ENODATA
                    break
                read_payload += bytes(val)
            elif name == "cmpxattr":
                # reference CEPH_OSD_OP_CMPXATTR (EQ): guard ops on an
                # xattr's current value; mismatch cancels the op
                _, key, ln = op
                want = bytes(msg.data[data_off:data_off + ln])
                data_off += ln
                have = cur_xattr(key)
                if have is None or bytes(have) != want:
                    result = -errno.ECANCELED
                    break
            elif name == "setxattr":
                _, key, ln = op
                val = bytes(msg.data[data_off:data_off + ln])
                txn.setattr(msg.oid, key, val)
                vattrs[key] = val
                data_off += ln
            elif name == "read":
                _, off, ln = op
                # existence through the staged view: a read after an
                # in-message delete is ENOENT even though the committed
                # object still exists until the txn applies
                if not cur_exists():
                    result = -errno.ENOENT
                    break
                if vbase_dropped:
                    # the committed bytes died with the in-vector
                    # delete: serve the staged recreate only (zeros
                    # base + this message's writes), never the
                    # pre-delete store content
                    size = cur_size() or 0
                    end = size if ln <= 0 else min(off + ln, size)
                    buf = np.zeros(max(end - off, 0), dtype=np.uint8)
                    objop = txn.ops.get(msg.oid)
                    for w in (objop.writes if objop else []):
                        lo, hi = max(off, w.offset), min(end, w.end)
                        if lo < hi:
                            buf[lo - off:hi - off] = \
                                w.data[lo - w.offset:hi - w.offset]
                    read_payload += buf.tobytes()
                else:
                    data = be.read(msg.oid, off, ln if ln > 0 else None)
                    read_payload += data.tobytes() \
                        if data is not None else b""
            elif name == "stat":
                size = cur_size()
                if size is None:
                    result = -errno.ENOENT
                else:
                    out_meta.append(["stat", size])
            elif name == "call":
                # server-side compute (reference CEPH_OSD_OP_CALL ->
                # ClassHandler dispatch, PrimaryLogPG.cc:5643)
                from .. import cls as cls_mod
                _, spec, inlen = op
                inp = bytes(msg.data[data_off:data_off + inlen])
                data_off += inlen
                cls_name, _, method = spec.partition(".")
                fn = cls_mod.get_method(cls_name, method)
                if fn is None:
                    result = -errno.EOPNOTSUPP
                    break
                ctx = cls_mod.ClsContext(self, state, msg.pgid.pgid,
                                         msg.oid)
                try:
                    read_payload += fn(ctx, inp)
                except cls_mod.ClsError as e:
                    result = -e.errno
                    break
                if ctx._pending_write is not None:
                    off_w, data_w = ctx._pending_write
                    txn.write(msg.oid, off_w,
                              np.frombuffer(data_w, dtype=np.uint8))
                    txn.truncate(msg.oid, off_w + len(data_w))
                for k, v in ctx._pending_attrs.items():
                    txn.setattr(msg.oid, k, v)
            elif name.startswith("omap"):
                # reference PrimaryLogPG.cc:5643 OMAP op cases; omap is
                # replicated-pool-only (EC pools lack omap support in
                # the reference too: pool SUPPORTS_OMAP flag)
                if state.kind == "ec":
                    result = -errno.EOPNOTSUPP
                    break
                from ..common import omap_codec as oc
                cid = self._cid(spg_t(msg.pgid.pgid, NO_SHARD))
                goid = ghobject_t(msg.oid, shard=NO_SHARD)
                if name == "omapsetkeys":
                    _, ln = op
                    kv, _end = oc.decode_kv(msg.data[data_off:
                                                     data_off + ln])
                    data_off += ln
                    txn.omap_setkeys(msg.oid, kv)
                elif name == "omaprmkeys":
                    _, ln = op
                    keys, _end = oc.decode_keys(msg.data[data_off:
                                                         data_off + ln])
                    data_off += ln
                    txn.omap_rmkeys(msg.oid, keys)
                elif name == "omapclear":
                    txn.omap_clear(msg.oid)
                elif name == "omapsetheader":
                    _, ln = op
                    txn.omap_setheader(
                        msg.oid, bytes(msg.data[data_off:data_off + ln]))
                    data_off += ln
                elif name in ("omapgetkeys", "omapgetvals"):
                    _, saln, maxret = op
                    (starts, _e) = oc.decode_keys(
                        msg.data[data_off:data_off + saln])
                    data_off += saln
                    start_after = starts[0] if starts else None
                    if not self._object_exists(state, msg.oid):
                        result = -errno.ENOENT
                        break
                    omap = self.store.omap_get(cid, goid)
                    ks = sorted(k for k in omap
                                if start_after is None or k > start_after)
                    if maxret > 0:
                        ks = ks[:maxret]
                    if name == "omapgetkeys":
                        read_payload += oc.encode_keys(ks)
                    else:
                        read_payload += oc.encode_kv(
                            {k: omap[k] for k in ks})
                elif name == "omapgetvalsbykeys":
                    _, ln = op
                    keys, _e = oc.decode_keys(
                        msg.data[data_off:data_off + ln])
                    data_off += ln
                    if not self._object_exists(state, msg.oid):
                        result = -errno.ENOENT
                        break
                    omap = self.store.omap_get(cid, goid)
                    read_payload += oc.encode_kv(
                        {k: omap[k] for k in keys if k in omap})
                elif name == "omapgetheader":
                    if not self._object_exists(state, msg.oid):
                        result = -errno.ENOENT
                        break
                    read_payload += self.store.omap_get_header(cid, goid)
                else:
                    result = -errno.EOPNOTSUPP
                    break
            elif name == "listwatchers":
                # reference CEPH_OSD_OP_LIST_WATCHERS (librados
                # rados_watchers_list).  Disconnected watchers are
                # FILTERED from the reply (a crashed lock owner must
                # not look alive) but stay registered — a lossless
                # session mid-reconnect gets its frames replayed on
                # resume, and deregistering it here would break that
                # delivery guarantee.
                import json as _json
                key = (msg.pgid.pgid.pool, msg.oid.name)
                with self.pg_lock:
                    live = sorted(
                        ck for ck, c in
                        self.watchers.get(key, {}).items()
                        if c.is_connected())
                read_payload += _json.dumps(live).encode()
            elif name == "watch":
                _, cookie = op
                key = (msg.pgid.pgid.pool, msg.oid.name)
                with self.pg_lock:
                    self.watchers.setdefault(key, {})[cookie] = conn
            elif name == "unwatch":
                _, cookie = op
                key = (msg.pgid.pgid.pool, msg.oid.name)
                with self.pg_lock:
                    self.watchers.get(key, {}).pop(cookie, None)
            elif name == "notify":
                _, ln = op
                payload = bytes(msg.data[data_off:data_off + ln])
                data_off += ln
                self._do_notify(msg.pgid.pgid, msg.oid, payload)
            else:
                result = -errno.EOPNOTSUPP
        if result == 0 and txn.ops and \
                self._live_shards(state) < self._pool_min_size(msg.pgid.pgid):
            # Below min_size an acked write could land on fewer than k
            # shards and be unrecoverable; block it (reference
            # PrimaryLogPG/PeeringState min_size enforcement).
            result = -errno.EAGAIN
        elif result == 0 and txn.ops:
            self.perf.inc("op_w")
            if self.pg_ledger.enabled:
                # >= min_size but < size: the write will ack while
                # some shard has no live home — the degraded-window
                # ledger counts exactly these acks (docs/TRACING.md
                # "Control plane")
                _pool = self.osdmap.pools.get(msg.pgid.pgid.pool)
                if _pool is not None and \
                        self._live_shards(state) < _pool.size:
                    self.pg_ledger.degraded_ack(msg.pgid.pgid)
            if msg.snapc and int(msg.snapc[0]) > 0:
                # copy-on-write before the mutation lands (reference
                # PrimaryLogPG::make_writeable)
                for woid, objop in list(txn.ops.items()):
                    self._maybe_cow(state, msg.pgid.pgid, woid,
                                    int(msg.snapc[0]),
                                    is_delete=objop.delete)
            done = threading.Event()
            window = float(self.cct.conf.get("tpu_batch_window_ms")
                           or 0)
            # version allocation and pipeline entry must be ATOMIC:
            # with ops running concurrently (sharded op pool), a later
            # version entering the FIFO pipeline first would commit out
            # of order and violate the PG log's monotonicity.  The
            # blocking metadata prefetch runs BEFORE the lock.
            staged = be.make_op(txn, done.set, top=top) \
                if state.kind == "ec" else None
            if window > 0 and state.kind == "ec":
                # dynamic batch window (SURVEY section 7 "hard parts",
                # BlueStore-deferred style): hold the pipeline drain
                # briefly so concurrent client ops encode in ONE codec
                # launch instead of one launch each.  Armed AFTER the
                # prefetch: the window must cover enqueue, not the
                # metadata RPCs.
                self._arm_batch_drain(be, window)
            with state.lock:
                version = state.next_version(self.osdmap.epoch)
                top.set_info("version", str(version))
                if staged is not None:
                    be.enqueue(staged, version)
                else:
                    be.submit_transaction(txn, version, done.set)
            if not done.wait(30):
                result = -errno.ETIMEDOUT
                top.mark_event("timeout")
            elif staged is not None and staged.error is not None:
                # pipeline failure containment acks with the error
                # attached instead of raising (docs/PIPELINE.md) — the
                # client must NOT see a failed write as durable
                result = -errno.EIO
            elif staged is None:
                # EC ops mark commit/failed inside the pipeline's
                # in-order finisher; replicated ops commit here
                top.mark_event("commit")
        elif result == 0:
            self.perf.inc("op_r")
        self.perf.tinc("op_latency", time.perf_counter() - _t0)
        top.mark_event("reply_sent")
        conn.send_message(M.MOSDOpReply(msg.tid, result, read_payload,
                                        self.osdmap.epoch))
        self.op_tracker.unregister(top, result)

    def _arm_batch_drain(self, be, window_ms: float) -> None:
        """One timer per backend per window: the first op entering an
        idle window holds the drain and schedules the release; ops
        arriving meanwhile pile into waiting_reads and flush together."""
        with self.pg_lock:
            armed = self._batch_armed.get(id(be))
            if armed:
                return
            self._batch_armed[id(be)] = True
        with be.lock:
            be._hold += 1

        def _release():
            with self.pg_lock:
                self._batch_armed[id(be)] = False
            # check_ops must run UNDER be.lock (the batch() context
            # manager's form): an unlocked drain races a concurrent
            # locked check_ops and double-plans the head op
            with be.lock:
                be._hold -= 1
                if be._hold == 0:
                    be.check_ops()

        t = threading.Timer(window_ms / 1000.0, _release)
        t.daemon = True
        t.start()

    # -- self-managed snapshots (reference SnapSet + make_writeable) --------

    def _head_snapset(self, state: PGState, pgid: pg_t,
                      head: hobject_t):
        from .snapset import SS_KEY, SnapSet
        be = state.backend
        if state.kind == "ec":
            for s in range(be.n):
                attrs = be.shards.get_attrs(s, head)
                if attrs is not None:
                    return SnapSet.decode(attrs.get(SS_KEY)), True
            return SnapSet(), False
        # replicated: the primary holds a full local copy
        goid = ghobject_t(head, shard=NO_SHARD)
        cid = self._cid(spg_t(pgid, NO_SHARD))
        try:
            attrs = self.store.getattrs(cid, goid)
        except KeyError:
            return SnapSet(), False
        return SnapSet.decode(attrs.get(SS_KEY)), True

    def _maybe_cow(self, state: PGState, pgid: pg_t, oid: hobject_t,
                   seq: int, is_delete: bool = False) -> None:
        """Clone the head to <oid, snap=seq> when the op's SnapContext
        is newer than what the head has seen.  A delete additionally
        parks the SnapSet on the snapdir object so a later recreate
        keeps the clone history (reference CEPH_SNAPDIR)."""
        from dataclasses import replace
        from .snapset import SNAPDIR, SS_KEY, SnapSet
        be = state.backend
        head = replace(oid, snap=0)
        snapdir = replace(oid, snap=SNAPDIR)
        if not is_delete and state.snap_seqs.get(head, -1) >= seq:
            return   # head already saw this snapc: no fetch, no COW
        ss, exists = self._head_snapset(state, pgid, head)
        if not exists:
            # (re)born under this snapc: snaps <= seq predate this
            # incarnation, but a snapdir left by a deleted predecessor
            # carries clone history that must survive
            prior, had_dir = self._head_snapset(state, pgid, snapdir)
            ss = SnapSet(seq=seq, clones=prior.clones if had_dir else [],
                         born=seq,
                         prior_born=prior.born if had_dir else 0)
            self._bcast_head_txn(state, pgid, head, None, ss)
            state.snap_seqs[head] = seq
            return
        if ss.needs_cow(seq):
            ss.add_clone(seq)
            self._bcast_head_txn(state, pgid, head,
                                 replace(head, snap=seq), ss)
        state.snap_seqs[head] = max(ss.seq, seq)
        if is_delete:
            # park the SnapSet for the next incarnation
            self._bcast_head_txn(state, pgid, snapdir, None, ss)
            state.snap_seqs.pop(head, None)

    def _bcast_head_txn(self, state: PGState, pgid: pg_t,
                        head: hobject_t, clone_to: hobject_t | None,
                        ss, timeout: float = 15.0) -> None:
        """Send clone+snapset (or snapset-only) transactions to every
        shard/replica and WAIT for the commits: a silently-failed clone
        would lose snapshot history while the triggering write goes on
        to succeed.  Session FIFO additionally orders these before the
        write that triggered the COW."""
        from .snapset import SS_KEY
        be = state.backend
        pending = {"n": 0}
        plock = threading.Lock()
        done = threading.Event()

        def on_commit(_sr) -> None:
            with plock:          # replies race on reader threads
                pending["n"] -= 1
                if pending["n"] <= 0:
                    done.set()

        if state.kind == "ec":
            pending["n"] = be.n
            for s in range(be.n):
                txn = Transaction()
                if clone_to is not None:
                    txn.clone(shard_oid(head, s), shard_oid(clone_to, s))
                txn.setattr(shard_oid(head, s), SS_KEY, ss.encode())
                be.shards.sub_write(s, txn, on_commit)
        else:
            pending["n"] = be.replicas.n_replicas
            for r in range(be.replicas.n_replicas):
                txn = Transaction()
                hg = ghobject_t(head, shard=NO_SHARD)
                if clone_to is not None:
                    txn.clone(hg, ghobject_t(clone_to, shard=NO_SHARD))
                txn.setattr(hg, SS_KEY, ss.encode())
                be.replicas.rep_write(r, txn, on_commit)
        if not done.wait(timeout):
            raise ErasureCodeError(
                errno.EAGAIN,
                f"snapshot COW of {head.name} did not commit everywhere")

    def _do_snap_read(self, conn, msg: M.MOSDOp, state: PGState) -> None:
        """Serve read/stat at a snap id by resolving the SnapSet to the
        covering clone (reference PrimaryLogPG::find_object_context
        with a snapid)."""
        from dataclasses import replace
        from .snapset import SNAPDIR
        be = state.backend
        head = replace(msg.oid, snap=0)
        ss, exists = self._head_snapset(state, msg.pgid.pgid, head)
        if not exists:
            # deleted head: its clone history lives on the snapdir
            ss, exists = self._head_snapset(
                state, msg.pgid.pgid, replace(msg.oid, snap=SNAPDIR))
        target_snap = ss.resolve(msg.oid.snap) if exists else None
        if target_snap == 0 and not self._object_exists(state, head):
            target_snap = None      # resolved to a deleted head
        if target_snap is None:
            conn.send_message(M.MOSDOpReply(
                msg.tid, -errno.ENOENT, b"", self.osdmap.epoch))
            return
        roid = head if target_snap == 0 else \
            replace(msg.oid, snap=target_snap)
        read_payload = b""
        result = 0
        for op in msg.ops:
            name = op[0]
            if name == "read":
                _, off, ln = op
                try:
                    data = be.read(roid, off, ln if ln > 0 else None)
                    read_payload += data.tobytes() \
                        if data is not None else b""
                except ErasureCodeError as e:
                    result = -e.errno
                    break
            elif name == "stat":
                pass
            else:
                result = -errno.EROFS   # snapshots are read-only
                break
        conn.send_message(M.MOSDOpReply(msg.tid, result, read_payload,
                                        self.osdmap.epoch))

    def _pool_min_size(self, pgid: pg_t) -> int:
        pool = self.osdmap.pools.get(pgid.pool)
        return pool.min_size if pool is not None else 1

    def _live_shards(self, state: PGState) -> int:
        """Count acting-set members that are placed and up."""
        from ..crush.map import CRUSH_ITEM_NONE
        be = state.backend
        tgt = be.shards if state.kind == "ec" else be.replicas
        return sum(1 for o in tgt.acting
                   if o != CRUSH_ITEM_NONE and self.osdmap.is_up(o))

    def _object_exists(self, state: PGState, oid: hobject_t) -> bool:
        be = state.backend
        if state.kind == "ec":
            return be.exists(oid)
        return be.stat(oid) is not None

    def _stat_logical(self, state: PGState, oid: hobject_t) -> int | None:
        be = state.backend
        if state.kind == "ec":
            size = be._get_size(oid)
            return size if size > 0 else (
                None if be.shards.stat(0, oid) is None else size)
        return be.stat(oid)

    # -- watch/notify (reference osd/Watch.h, PrimaryLogPG notify) ----------

    def _do_notify(self, pgid: pg_t, oid: hobject_t,
                   payload: bytes, timeout: float = 5.0) -> None:
        key = (pgid.pool, oid.name)
        with self.pg_lock:
            # skip (but keep registered) disconnected watchers: waiting
            # the full ack timeout on a dead connection stalls every
            # notify, but a lossless session mid-reconnect must keep
            # its registration for replay delivery
            targets = {ck: c for ck, c in
                       self.watchers.get(key, {}).items()
                       if c.is_connected()}
            self._notify_id += 1
            nid = self._notify_id
        if not targets:
            return
        ev = threading.Event()
        self._notify_pending[nid] = {
            "remaining": set(targets), "event": ev}
        for cookie, conn in targets.items():
            try:
                conn.send_message(M.MWatchNotify(oid, nid, cookie,
                                                 payload))
            except Exception:  # noqa: BLE001 - dead watcher
                self._notify_pending[nid]["remaining"].discard(cookie)
        ev.wait(timeout)
        self._notify_pending.pop(nid, None)

    # -- scrub (asok-driven AND background-scheduled; reference
    #    `ceph pg scrub` + PG::sched_scrub) ---------------------------------

    def _scrub_led_pgs(self, deep: bool, repair: bool) -> dict:
        """Scrub every EC PG this OSD currently leads."""
        from . import scrub as scrub_mod
        out = {}
        for pool in list(self.osdmap.pools.values()):
            if not pool.is_erasure():
                # replicated pools: no EC scrub, but snap trim applies
                for seed in range(pool.pg_num):
                    pgid = pg_t(pool.id, seed)
                    _, acting, _, primary = \
                        self.osdmap.pg_to_up_acting_osds(pgid)
                    if primary != self.osd_id:
                        continue
                    try:
                        state = self._get_pg(pgid)
                    except ErasureCodeError:
                        continue   # unpeered PG: skip this round
                    names = self._pg_object_names(pgid, acting, [0])
                    trimmed = self._trim_snaps(state, pgid, names)
                    if trimmed:
                        out[str(pgid)] = {"objects": len(names),
                                          "errors": [], "repaired": 0,
                                          "snaps_trimmed": trimmed}
                continue
            for seed in range(pool.pg_num):
                pgid = pg_t(pool.id, seed)
                _, acting, _, primary = \
                    self.osdmap.pg_to_up_acting_osds(pgid)
                if primary != self.osd_id:
                    continue
                try:
                    state = self._get_pg(pgid)
                except ErasureCodeError:
                    continue   # unpeered PG: scrub it next round
                names = sorted(self._pg_object_names(
                    pgid, acting, range(state.backend.n)),
                    key=lambda o: o.name)
                use_device = None  # platform default
                if not bool(self.cct.conf.get("osd_deep_scrub_device")):
                    use_device = False
                res = scrub_mod.scrub_pg(state.backend, names, deep=deep,
                                         repair=repair,
                                         use_device=use_device)
                trimmed = self._trim_snaps(state, pgid, names)
                out[str(pgid)] = {
                    "objects": res.objects,
                    "errors": [[e.oid.name, e.shard, e.kind, e.detail]
                               for e in res.errors],
                    "repaired": len(res.repaired),
                    "snaps_trimmed": trimmed,
                    "device_bytes": res.device_bytes,
                    "host_bytes": res.host_bytes,
                }
        return out

    def _asok_scrub(self, cmd: dict) -> dict:
        # scrub runs are tracked ops too (reference: scrubs surface in
        # dump_ops_in_flight / slow-op checks like client ops)
        top = self.op_tracker.create(
            "scrub", f"deep={bool(cmd.get('deep', True))}")
        top.mark_event("scrub_start")
        try:
            out = self._scrub_led_pgs(
                deep=bool(cmd.get("deep", True)),
                repair=bool(cmd.get("repair", False)))
        except Exception:
            top.mark_event("failed")
            self.op_tracker.unregister(top, -errno.EIO)
            raise
        top.mark_event("scrub_done")
        self.op_tracker.unregister(top, 0)
        return out

    # -- multichip mesh plane (docs/MULTICHIP.md) ---------------------------

    def _mesh_service(self):
        """The per-host MeshService when osd_ec_use_mesh is on; None
        otherwise (EC backends then run the single-chip plane).
        Configuration failures (not enough devices, bad shape) are
        logged config errors, never daemon-fatal."""
        if not bool(self.cct.conf.get("osd_ec_use_mesh")):
            return None
        from ..parallel.service import MeshService
        try:
            return MeshService.get_or_configure(
                str(self.cct.conf.get("mesh_devices")))
        except Exception as e:  # noqa: BLE001 — MeshError et al.
            self.cct.dout("osd", 1,
                          f"mesh service unavailable ({e}); EC PGs "
                          f"will use the single-chip plane")
            return None

    def _host_launch_queue(self):
        """The per-host EC launch queue (cross-PG continuous batching,
        parallel/launch_queue.py) when osd_ec_host_batch is on; None
        otherwise (each PG then launches its own drains).  Handed out
        through the MeshService seam — it brokers the device plane, so
        it brokers the launch queue — and works with or without a
        configured mesh.  The queue's perf counters (launches,
        coalescing, occupancy, lat_ec_batch_wait) register into
        exactly ONE daemon's collection per host (the first to wire
        the queue) so `perf dump` / `dump_latencies` / the prometheus
        exporter surface them ONCE: the set is host-level, and every
        daemon re-exporting the shared singleton would make the
        normal sum-across-daemons aggregation read n_daemons times
        the real launch/byte counts.  Every daemon still serves the
        host truth via the `launch queue status` asok."""
        if not bool(self.cct.conf.get("osd_ec_host_batch")):
            return None
        from ..parallel.service import MeshService
        queue = MeshService.host_launch_queue(
            window_us=float(self.cct.conf.get(
                "osd_ec_host_batch_window_us")),
            max_bytes=int(self.cct.conf.get(
                "osd_ec_host_batch_max_bytes")))
        if not getattr(queue, "_perf_registered", False):
            queue._perf_registered = True
            self.cct.perf.add(queue.perf)
        return queue

    def _asok_launch_queue_status(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok launch queue status`: the host
        queue's batching knobs + launch/coalescing/occupancy
        aggregates, plus this OSD's per-PG routed-drain counts — an
        operator reads occupancy % and runs-per-launch here to see
        whether PG fan-out is actually coalescing."""
        from ..parallel.launch_queue import ECLaunchQueue
        queue = ECLaunchQueue.host_get()
        with self.pg_lock:
            pgs = {
                str(pgid): st.backend.perf.dump().get(
                    "ec_host_queue_drains", 0)
                for pgid, st in self.pgs.items() if st.kind == "ec"}
        return {
            "osd": self.osd_id,
            "enabled": bool(self.cct.conf.get("osd_ec_host_batch")),
            "queue": queue.status() if queue is not None else None,
            "pg_queue_drains": pgs,
        }

    def _asok_repair_status(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok repair status` (docs/REPAIR.md):
        recovery backlog + throttle knobs + the scheduler's recovery-
        class serve counts, and each led EC PG's repair ledger
        (helper-bytes-read vs reconstructed-bytes — the CLAY savings —
        plus reconstruct-on-read / read-timeout provenance)."""
        from ..parallel.launch_queue import ECLaunchQueue
        with self.pg_lock:
            pgs = {str(pgid): st.backend.repair_status()
                   for pgid, st in self.pgs.items()
                   if st.kind == "ec"}
            needing = sorted(str(p)
                             for p in self._pgs_needing_recovery)
            inflight = self._recovery_inflight
            unfound = {str(p): len(objs)
                       for p, objs in self._unfound.items()}
        sched = None
        if self.op_wq is not None:
            sched = self.op_wq.dump().get("classes", {}).get("recovery")
        perf = self.perf.dump()
        queue = ECLaunchQueue.host_get()
        qst = queue.status() if queue is not None else {}
        return {
            "osd": self.osd_id,
            "recovery": {
                "inflight_passes": inflight,
                "pgs_needing_recovery": needing,
                "unfound": unfound,
                "queued_ops": perf.get("recovery_queued_ops", 0),
                "pushed_bytes": perf.get("recovery_pushed_bytes", 0),
                "throttle": {
                    "max_bytes_per_sec": int(self.cct.conf.get(
                        "osd_recovery_max_bytes_per_sec") or 0),
                    "sleep_s": float(self.cct.conf.get(
                        "osd_recovery_sleep") or 0.0),
                    "wait": perf.get("recovery_throttle_wait"),
                },
            },
            "scheduler_recovery_class": sched,
            "host_queue": {
                "decode_launches": qst.get("decode_launches", 0),
                "repair_launches": qst.get("repair_launches", 0),
            },
            "stuck_subwrites": self._stuck_subwrites(),
            "pgs": pgs,
        }

    def _stuck_subwrites(self, mark: bool = False) -> list[dict]:
        """EC client writes whose shard sub-writes have been in
        flight past osd_stuck_subwrite_s (the PR 16 known reduction:
        an op wedged across a SIGKILL re-peer used to stall
        active+clean waits with no trace).  Surfaces each as
        stuck_subwrite(pg) in `repair status`; with mark=True the
        event is stamped on the op's timeline ONCE so slow-op blame
        names it instead of a bare 'waiting after sub_write_sent'."""
        raw = self.cct.conf.get("osd_stuck_subwrite_s")
        thresh = 10.0 if raw is None else float(raw)
        if thresh <= 0:
            return []
        now = time.time()
        out: list[dict] = []
        with self.pg_lock:
            ec_pgs = [(pgid, st.backend)
                      for pgid, st in self.pgs.items()
                      if st.kind == "ec"]
        for pgid, be in ec_pgs:
            with be.lock:
                waiting = list(be.waiting_commit)
            for op in waiting:
                if op.state != "committing" or \
                        op.pending_commits <= 0:
                    continue
                top = op.top
                age = (now - top.initiated_at) \
                    if getattr(top, "is_tracked", False) else None
                if age is None or age < thresh:
                    continue
                blame = f"stuck_subwrite({pgid})"
                if mark and not any(n == blame
                                    for _, n in top.events):
                    top.mark_event(blame)
                out.append({
                    "pg": str(pgid),
                    "blame": blame,
                    "age_s": round(age, 3),
                    "pending_shards": op.pending_commits,
                    "version": str(op.version),
                    "trace_id": top.trace.trace_id
                    if top.trace is not None else None,
                })
        return out

    def _asok_pg_ledger(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok pg ledger` (docs/TRACING.md
        "Control plane"): the per-PG state-machine ledger — current
        state + bounded transition ring per PG, peering/recovery
        stage decomposition, O(peers) scan counters, degraded
        windows, and the lat_peering_*/lat_recovery_* percentile
        summaries."""
        out = self.pg_ledger.dump(
            last=int(cmd["last"]) if "last" in cmd else 8)
        out["osd"] = self.osd_id
        out["pg_state_counts"] = self.pg_ledger.pg_state_counts()
        return out

    def _asok_messenger_status(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok messenger status` (docs/TRACING.md
        "Wire plane"): reactor health (per-reactor loop lag, lag
        events), dispatch-executor depth/high-water and qwait/dispatch
        latency summaries, plus this daemon's wire totals."""
        out = self.messenger.ledger.status()
        out["osd"] = self.osd_id
        out["host_perf_owner"] = self._msgr_reporter
        out["reactors_conf"] = int(
            self.cct.conf.get("ms_async_op_threads")) or None
        out["daemon"] = self.messenger.stats.totals()
        return out

    def _asok_conn_profile(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok conn profile`: per-peer wire
        accounting — msgs/bytes in/out by message type, send-queue
        high-water, reconnects, replay frames, compress/encrypt
        bytes — from this daemon's bounded per-peer ring."""
        out = self.messenger.ledger.conn_profile(
            last=int(cmd["last"]) if "last" in cmd else None)
        out["osd"] = self.osd_id
        return out

    def _asok_launch_profile(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok launch profile`: the host flight
        recorder's launch ledger — aggregates, lat_launch_* percentile
        summaries, and the bounded ring of recent launches (each with
        launch id, jit bucket, runs/bytes/pg-mix, queue-wait, submit
        and device times, and the contributing ops' trace ids)."""
        out = self._profiler.profile(
            last=int(cmd["last"]) if "last" in cmd else None)
        out["osd"] = self.osd_id
        out["host_perf_owner"] = self._profiler_reporter
        return out

    def _maybe_prewarm(self) -> None:
        """Boot-time jit-bucket prewarm (ops/prewarm.py, conf
        osd_ec_prewarm): compile the expected bucket set BEFORE
        MOSDBoot, so the daemon never reports `up` with cold jit
        caches.  Process-level: the first in-process daemon to boot
        warms for the host (the caches are process-global); later
        booters reuse its status.  Never fails the boot."""
        if not bool(self.cct.conf.get("osd_ec_prewarm")):
            return
        try:
            from ..ec.interface import Profile
            from ..ec.registry import ErasureCodePluginRegistry
            from ..ops import prewarm
            prof = Profile(dict(
                kv.split("=", 1) for kv in str(self.cct.conf.get(
                    "osd_pool_default_erasure_code_profile")).split()
                if "=" in kv))
            codec = ErasureCodePluginRegistry.instance().factory(
                prof.get("plugin", "jax") or "jax", prof)
            self._prewarm_status = prewarm.run_once(
                codec, profiler=self._profiler,
                budget_s=float(self.cct.conf.get(
                    "osd_ec_prewarm_budget_s")))
        except Exception as e:  # noqa: BLE001 — never a boot dependency
            self._prewarm_status = {"error": repr(e)}

    def _asok_prewarm_status(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok prewarm status`: the boot prewarm
        pass's plan/coverage/budget outcome plus the host-level
        prewarm tallies and persistent-cache state."""
        from ..ops import compile_cache, prewarm
        out = {
            "osd": self.osd_id,
            "enabled": bool(self.cct.conf.get("osd_ec_prewarm")),
            "boot": self._prewarm_status or prewarm.last_status(),
            "host": self._profiler.prewarm_summary(),
            "persistent_cache": compile_cache.status(),
        }
        return out

    def _asok_compile_ledger(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok compile ledger`: per-host compile
        attribution — every first-seen jit bucket with first-hit vs
        steady-state submit times (the difference is the compile),
        stall counts, and the COMPILE_STORM window summary."""
        out = self._profiler.compile_ledger()
        out["osd"] = self.osd_id
        out["storm_budget_s"] = float(self.cct.conf.get(
            "osd_ec_compile_storm_budget_s"))
        return out

    def _asok_mesh_status(self, cmd: dict) -> dict:
        """`ceph daemon osd.N.asok mesh status`: the host service's
        mesh + per-PG plane state (active / fallen-back / config
        error), so an operator can see exactly which plane serves
        which PG and why."""
        from ..parallel.service import MeshService
        svc = MeshService.get()
        with self.pg_lock:
            pgs = {str(pgid): st.backend.mesh_status()
                   for pgid, st in self.pgs.items()
                   if st.kind == "ec"}
        return {
            "osd": self.osd_id,
            "use_mesh": bool(self.cct.conf.get("osd_ec_use_mesh")),
            "mesh_devices": str(self.cct.conf.get("mesh_devices")),
            "service": svc.status() if svc is not None else None,
            "pgs": pgs,
        }

    # -- snap trim (reference PrimaryLogPG SnapTrimmer / snap trim queue;
    #    runs with scrub here: both walk the same object listing) ----------

    def _trim_snaps(self, state: PGState, pgid: pg_t, names) -> int:
        """Reclaim clones whose entire covered snap interval is in the
        pool's removed_snaps.  Resolution means clone c serves snaps in
        (max(prev_clone, born), c]; when every id in that window is
        deleted, nothing can ever read the clone again."""
        from dataclasses import replace
        from .snapset import SNAPDIR, SnapSet
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None or not pool.removed_snaps:
            return 0
        removed = set(pool.removed_snaps)
        be = state.backend
        trimmed = 0
        for head in {replace(o, snap=0) for o in names}:
            src = head
            ss, exists = self._head_snapset(state, pgid, src)
            if not exists:
                src = replace(head, snap=SNAPDIR)
                ss, exists = self._head_snapset(state, pgid, src)
                if not exists:
                    continue
            keep, lower, changed = [], 0, False
            for c in sorted(ss.clones):
                lo = max(lower, ss.born)
                window = set(range(lo + 1, c + 1))
                if window and window <= removed:
                    clone_oid = replace(head, snap=c)
                    if state.kind == "ec":
                        for s in range(be.n):
                            txn = Transaction()
                            txn.remove(shard_oid(clone_oid, s))
                            be.shards.sub_write(s, txn,
                                                lambda _s: None)
                    else:
                        for r in range(be.replicas.n_replicas):
                            txn = Transaction()
                            txn.remove(ghobject_t(clone_oid,
                                                  shard=NO_SHARD))
                            be.replicas.rep_write(r, txn,
                                                  lambda _r: None)
                    trimmed += 1
                    changed = True
                else:
                    keep.append(c)
                lower = c
            if changed:
                ss.clones = keep
                try:
                    self._bcast_head_txn(state, pgid, src, None, ss)
                except ErasureCodeError:
                    pass   # next trim pass retries
                state.snap_seqs.pop(head, None)
        return trimmed

    def _scrub_loop(self) -> None:
        """Background scheduler (reference PG scrub scheduling with
        min/deep intervals): shallow every osd_scrub_interval, deep
        every osd_deep_scrub_interval, optional auto-repair."""
        conf = self.cct.conf
        last_deep = time.time()
        interval = float(conf.get("osd_scrub_interval"))
        while not self._hb_stop.wait(interval):
            try:
                interval = float(conf.get("osd_scrub_interval"))
                deep_iv = float(conf.get("osd_deep_scrub_interval"))
                repair = bool(conf.get("osd_scrub_auto_repair"))
                deep = time.time() - last_deep >= deep_iv
                if deep:
                    last_deep = time.time()
                out = self._scrub_led_pgs(deep=deep, repair=repair)
                nerr = sum(len(r["errors"]) for r in out.values())
                if nerr:
                    self.cct.dout("osd", 1,
                                  f"background scrub: {nerr} errors "
                                  f"across {len(out)} pgs")
            except Exception as e:  # noqa: BLE001 - scheduler survives
                self.cct.dout("osd", 1, f"background scrub failed: {e!r}")

    # -- op tracking surveillance (reference OSD::check_ops_in_flight
    #    tick + the SLOW_OPS health path) -----------------------------------

    def _asok_dump_ops_in_flight(self, cmd: dict) -> dict:
        """Tracker-backed dump_ops_in_flight.  Keeps the pre-tracker
        output keys (pg / state / version) for compatibility and adds
        the tracker surface (age, current stage, trace id, events)."""
        if not self.op_tracker.enabled:
            # the reference returns an explicit error here; an empty
            # dump would affirmatively claim nothing is in flight
            return {"num_ops": 0, "ops": [],
                    "error": "op tracking disabled "
                             "(osd_enable_op_tracker=false)"}
        d = self.op_tracker.dump_ops_in_flight()
        for op in d["ops"]:
            op.setdefault("pg", "")
            op.setdefault("version", "0'0")
            op["state"] = op.get("current_stage", "")
        return d

    def _optrack_interval(self) -> float:
        ct = self.op_tracker.complaint_time
        return min(1.0, max(0.05, ct / 4.0)) if ct > 0 else 1.0

    def _optrack_loop(self) -> None:
        """Slow-op surveillance: latch over-complaint ops, report them
        to the mon (MOSDSlowOpReport -> `health` SLOW_OPS warning),
        and send one clearing report when the last slow op ages out so
        the warning retires."""
        last = 0
        while not self._hb_stop.wait(self._optrack_interval()):
            try:
                if not self.op_tracker.enabled:
                    if last:
                        # tracking turned off mid-warning: clear it at
                        # the mon instead of leaving it to go stale
                        self.mon_conn.send_message(M.MOSDSlowOpReport(
                            self.osd_id, {"count": 0, "oldest_age": 0.0,
                                          "ops": []}))
                        last = 0
                    continue
                # stamp wedged EC sub-writes (PR 16's known reduction:
                # a commit lost across a SIGKILL re-peer) onto their
                # op timelines so blame() names stuck_subwrite(pg)
                # instead of a generic "waiting after sub_write_sent"
                self._stuck_subwrites(mark=True)
                rep = self.op_tracker.slow_op_summary()
                if rep["count"] or last:
                    self.mon_conn.send_message(
                        M.MOSDSlowOpReport(self.osd_id, rep))
                last = rep["count"]
            except Exception:  # noqa: BLE001 - mon electing/shutdown
                pass

    # -- PG stats reporting (reference MPGStats via the mgr: the
    #    degraded/misplaced/unfound counts behind `ceph pg stat`,
    #    PG_DEGRADED health, and the split/merge interleave guard) ---------

    def _compile_pg_stats(self) -> dict:
        """Summarize this OSD's recovery/split/merge state: led PGs
        with recovery pending (degraded), objects with split/merge
        pushes in flight (misplaced), and latched-unfound objects,
        per pool and in total."""
        with self.pg_lock:
            needing = list(self._pgs_needing_recovery)
            # undersized-but-recovered PGs (down-not-out holes) are
            # degraded too — without them a down OSD whose data all
            # re-peered is invisible to PG_DEGRADED and mgr progress
            needing += [p for p in self._pgs_undersized
                        if p not in self._pgs_needing_recovery]
            pushes = list(self._split_push_pending)
            unfound = {pg: len(objs)
                       for pg, objs in self._unfound.items()}
            recovering = self._recovery_inflight
        pools: dict[str, dict] = {}

        def pool_rec(pool_id: int) -> dict:
            return pools.setdefault(str(pool_id), {
                "degraded_pgs": 0, "misplaced": 0, "unfound": 0,
                "push_seeds": []})

        for pgid in needing:
            pool_rec(pgid.pool)["degraded_pgs"] += 1
        seen_seeds: dict[str, set] = {}
        for child, _h in pushes:
            rec = pool_rec(child.pgid.pool)
            rec["misplaced"] += 1
            seen_seeds.setdefault(str(child.pgid.pool),
                                  set()).add(child.pgid.seed)
        for pid, seeds in seen_seeds.items():
            pools[pid]["push_seeds"] = sorted(seeds)[:128]
        for pg, n in unfound.items():
            pool_rec(pg.pool)["unfound"] += n
        rep = {
            "degraded_pgs": len(needing),
            "misplaced": len(pushes),
            "unfound": sum(unfound.values()),
            "recovering": recovering,
            "epoch": self.osdmap.epoch,
            "pools": pools,
        }
        # compile attribution monward (COMPILE_STORM, mon/monitor.py):
        # only the host profiler's perf-owner daemon reports — the
        # recorder is a HOST singleton, and every co-hosted daemon
        # re-reporting it would make the mon's sum read n_daemons x
        # the real compile seconds (the launch-queue perf rule)
        # control-plane ledger block (docs/TRACING.md "Control plane"):
        # cumulative, coarsely rounded, None while nothing happened —
        # so steady-state reports stay bit-identical and the
        # _pgstats_should_send dedup keeps its keepalive cadence
        lb = self.pg_ledger.pgstats_block()
        if lb is not None:
            rep["ledger"] = lb
        if self._profiler_reporter and self._profiler.enabled:
            w = self._profiler.compile_report()
            if w["events"]:
                rep["compile"] = {
                    "window_s": w["window_s"],
                    "compile_s": w["compile_s"],
                    "stalls": w["stalls"],
                    "worst_bucket": w["worst_bucket"],
                    "worst_s": w["worst_s"],
                    "budget_s": float(self.cct.conf.get(
                        "osd_ec_compile_storm_budget_s")),
                }
        # wire-plane ledger block (MSGR_REACTOR_LAG, mon/monitor.py):
        # same perf-owner rule as compile — the reactor pool is a host
        # singleton, so only one co-hosted daemon ships its lag
        # window; None while the window is empty keeps steady-state
        # reports bit-identical for the dedup above
        if self._msgr_reporter:
            mb = self.messenger.ledger.pgstats_block()
            if mb is not None:
                rep["msgr"] = mb
        return rep

    def _pgstats_should_send(self, rep: dict, now: float) -> bool:
        """A CHANGED report sends immediately (the mon's gates need
        fresh truth); an unchanged one only re-sends at the slower
        osd_pg_stat_keepalive cadence to refresh the mon's freshness
        window — steady state is O(cluster / keepalive) instead of
        O(cluster / tick) mon-bound report traffic."""
        if rep != self._pgstats_last_sent:
            return True
        return now - self._pgstats_last_time >= \
            float(self.cct.conf.get("osd_pg_stat_keepalive"))

    def _pgstats_loop(self) -> None:
        conf = self.cct.conf
        while not self._hb_stop.wait(
                float(conf.get("osd_pg_stat_interval") or 0.5)):
            try:
                rep = self._compile_pg_stats()
                self.perf.set("pg_degraded", rep["degraded_pgs"])
                self.perf.set("pg_misplaced", rep["misplaced"])
                self.perf.set("pg_unfound", rep["unfound"])
                now = time.time()
                if self._pgstats_should_send(rep, now):
                    self.mon_conn.send_message(
                        M.MPGStats(self.osd_id, rep))
                    self._pgstats_last_sent = rep
                    self._pgstats_last_time = now
            except Exception:  # noqa: BLE001 - mon electing/shutdown
                pass

    # -- heartbeats (reference OSD::handle_osd_ping / failure_queue) --------

    def _heartbeat_peers(self) -> list[int]:
        """Bounded heartbeat peer subset (reference OSD::maybe_update_
        heartbeat_peers + osd_heartbeat_min_peers): ring neighbors by
        OSD id.  Small clusters keep the full mesh; above the target
        count each OSD pings only ~osd_heartbeat_min_peers neighbors,
        and — because ring selection is symmetric — remains WATCHED by
        about as many, so the mon's failure-reporter quorum still
        trips without the O(N^2)-per-tick ping mesh."""
        import bisect
        peers = sorted(o.id for o in self.osdmap.osds.values()
                       if o.up and o.id != self.osd_id)
        want = max(2, int(self.cct.conf.get("osd_heartbeat_min_peers")))
        if len(peers) <= want:
            return peers
        i = bisect.bisect_left(peers, self.osd_id)
        half = (want + 1) // 2
        sel = {peers[(i + k) % len(peers)] for k in range(half)}
        sel |= {peers[(i - 1 - k) % len(peers)] for k in range(half)}
        return sorted(sel)

    def _note_hb_tick_lag(self, now_mono: float) -> float:
        """Tick-lag detector (the compile-stall flap evidence PR 8's
        note asked for): seconds this tick started past its
        osd_heartbeat_interval schedule.  Sets the hb_tick_lag gauge
        every tick; a tick a full extra interval late counts in
        hb_tick_lag_events and logs — so when heartbeat grace trips,
        `perf dump` + the log say whether the DAEMON was starved
        (compile stall, GIL, load) rather than the peer dead."""
        last, self._hb_last_tick = self._hb_last_tick, now_mono
        if last is None:
            return 0.0
        lag = (now_mono - last) - self.heartbeat_interval
        self.perf.set("hb_tick_lag", round(max(0.0, lag), 6))
        # the inter-tick gap legitimately includes the previous
        # body's work (pings, mon RPC), so the event/log threshold
        # is a FULL extra interval — the ping cadence effectively
        # halved, eating real margin out of peers' grace windows —
        # not the half-interval a busy healthy body routinely costs
        if lag >= self.heartbeat_interval:
            self.perf.inc("hb_tick_lag_events")
            self.cct.dout(
                "osd", 1,
                f"heartbeat tick delayed {lag:.3f}s past "
                f"osd_heartbeat_interval={self.heartbeat_interval}s "
                f"(loop starved: first-bucket compile / load?)")
        return lag

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            self._note_hb_tick_lag(time.perf_counter())
            now = time.time()
            # mon keepalive + hunting: no map traffic for too long means
            # our mon may be dead — rotate to the next one and
            # re-announce (reference MonClient::tick hunting).  The
            # keepalive carries our epoch, so a current daemon's tick
            # earns a ~zero-byte ack instead of a full-map payload
            # (counted in the mon's map_keepalive_sends).
            try:
                self.mon_conn.send_message(
                    M.MMonGetMap(have_epoch=self.osdmap.epoch))
                stale = max(2.0, 4 * self.heartbeat_interval)
                if len(self.mon_addrs) > 1 and \
                        now - self._last_map_time > stale:
                    self._mon_idx = (self._mon_idx + 1) % \
                        len(self.mon_addrs)
                    self.mon_conn = self.messenger.connect(
                        self.mon_addrs[self._mon_idx])
                    self._last_map_time = now
                    self.mon_conn.send_message(
                        M.MMonGetMap(have_epoch=self.osdmap.epoch))
                    self.mon_conn.send_message(
                        M.MOSDBoot(self.osd_id, self.addr))
            except Exception:  # noqa: BLE001
                pass
            peers = [self.osdmap.osds[oid]
                     for oid in self._heartbeat_peers()
                     if oid in self.osdmap.osds]
            for o in peers:
                try:
                    # lossy: a dead peer must not accumulate a replay
                    # window of stale pings (reference runs heartbeats on
                    # dedicated lossy messengers)
                    self.messenger.connect(
                        tuple(o.addr), lossless=False).send_message(
                        M.MOSDPing(self.osd_id, self.osdmap.epoch,
                                   stamp=now))
                except Exception:  # noqa: BLE001
                    pass
                # A peer that has never answered counts from its first
                # ping, so silence-from-birth is also reported (reference
                # OSD.cc:5210 ping accounting tracks first_tx per peer).
                self._hb_first_ping.setdefault(o.id, now)
                last = self._hb_last_seen.get(o.id,
                                              self._hb_first_ping[o.id])
                # osd_heartbeat_grace was declared but never read —
                # the multiplier was hardcoded at its default of 4;
                # loaded many-daemon boxes need it tunable
                grace = self.heartbeat_interval * \
                    float(self.cct.conf.get("osd_heartbeat_grace"))
                if now - last > grace:
                    self.mon_conn.send_message(M.MOSDFailure(
                        self.osd_id, o.id, self.osdmap.epoch))

    def _handle_ping(self, conn, msg: M.MOSDPing) -> None:
        self._hb_last_seen[msg.from_osd] = time.time()
        if not msg.is_reply:
            conn.send_message(M.MOSDPing(self.osd_id, self.osdmap.epoch,
                                         is_reply=True, stamp=msg.stamp))
