"""ExtentCache: in-flight written extents for overlapping EC overwrites.

Re-expresses reference src/osd/ExtentCache.{h,cc}: while a write's
sub-ops are in flight, its stripe-aligned extents stay readable by
later ops in the pipeline, so an overlapping RMW doesn't re-read stale
bytes from the store (reserve/present/release around the pipeline,
reference ECBackend.cc:1902,1959,2020).  Ref-counted per extent: the
same range may be pinned by several queued ops.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from .types import hobject_t


@dataclass
class _Extent:
    off: int
    data: np.ndarray
    refs: int = 1

    @property
    def end(self) -> int:
        return self.off + self.data.size


class ExtentCache:
    def __init__(self) -> None:
        self._objs: dict[hobject_t, list[_Extent]] = {}
        self._lock = threading.Lock()

    def present(self, oid: hobject_t, off: int, data: np.ndarray) -> None:
        """Pin an assembled extent (newest data wins on overlap)."""
        with self._lock:
            exts = self._objs.setdefault(oid, [])
            for e in exts:
                if e.off == off and e.data.size == data.size:
                    e.data = np.asarray(data, dtype=np.uint8).copy()
                    e.refs += 1
                    return
            exts.append(_Extent(off,
                                np.asarray(data, dtype=np.uint8).copy()))

    def overlay(self, oid: hobject_t, off: int,
                buf: np.ndarray) -> np.ndarray:
        """Copy any cached bytes intersecting [off, off+len(buf)) over
        buf (newest extents last in the list = freshest)."""
        with self._lock:
            exts = list(self._objs.get(oid, []))
        end = off + buf.size
        for e in exts:
            lo, hi = max(off, e.off), min(end, e.end)
            if lo < hi:
                buf[lo - off:hi - off] = e.data[lo - e.off:hi - e.off]
        return buf

    def release(self, oid: hobject_t, off: int, length: int) -> None:
        with self._lock:
            exts = self._objs.get(oid)
            if not exts:
                return
            for e in list(exts):
                if e.off == off and e.data.size == length:
                    e.refs -= 1
                    if e.refs <= 0:
                        exts.remove(e)
                    break
            if not exts:
                del self._objs[oid]

    def clear_object(self, oid: hobject_t) -> None:
        with self._lock:
            self._objs.pop(oid, None)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._objs.values())
