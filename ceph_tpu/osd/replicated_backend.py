"""ReplicatedBackend: primary-copy replication.

Re-expresses reference src/osd/ReplicatedBackend.{h,cc}: the primary
applies the full transaction locally and ships it whole to each replica
(MOSDRepOp role — carried here by the same wire transaction envelope the
EC path uses), acking the client when all commit.  No RMW, no shards:
each replica holds the complete object.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from ..store.object_store import Transaction
from .ec_transaction import PGTransaction
from .pg_log import LogEntry, LogOp, PGLog
from .types import eversion_t, ghobject_t, hobject_t, NO_SHARD


class ReplicaBackend:
    """Transport seam to the replica set (primary's view); replica index
    0 is the primary itself."""

    n_replicas: int

    def rep_write(self, replica: int, txn: Transaction,
                  on_commit: Callable[[int], None]) -> None:
        raise NotImplementedError

    def local_read(self, oid: hobject_t, off: int,
                   length: int | None) -> np.ndarray:
        raise NotImplementedError

    def local_stat(self, oid: hobject_t) -> int | None:
        raise NotImplementedError


class LocalReplicaBackend(ReplicaBackend):
    """All replicas in one store (tests / single-host)."""

    def __init__(self, store, pgid, n_replicas: int):
        from .types import spg_t
        self.store = store
        self.n_replicas = n_replicas
        self.cids = {r: spg_t(pgid, NO_SHARD) if r == 0
                     else spg_t(pgid, -(r + 1)) for r in range(n_replicas)}
        for cid in self.cids.values():
            store.create_collection(cid)

    def rep_write(self, replica, txn, on_commit):
        self.store.queue_transactions(self.cids[replica], [txn])
        on_commit(replica)

    def local_read(self, oid, off, length):
        try:
            return self.store.read(self.cids[0],
                                   ghobject_t(oid, shard=NO_SHARD),
                                   off, length)
        except KeyError:
            return np.empty(0, dtype=np.uint8)

    def local_stat(self, oid):
        try:
            return self.store.stat(self.cids[0],
                                   ghobject_t(oid, shard=NO_SHARD))
        except KeyError:
            return None


class ReplicatedBackend:
    def __init__(self, replicas: ReplicaBackend, log: PGLog | None = None):
        self.replicas = replicas
        self.log = log or PGLog()
        self.lock = threading.RLock()
        self.completed = 0

    @staticmethod
    def _whole_oid(oid: hobject_t) -> ghobject_t:
        return ghobject_t(oid, shard=NO_SHARD)

    def _to_store_txn(self, txn: PGTransaction,
                      version: eversion_t | None = None) -> Transaction:
        t = Transaction()
        for oid, op in txn.ops.items():
            goid = self._whole_oid(oid)
            if op.delete:
                t.remove(goid)
                if not (op.writes or op.attrs or op.omap_ops or
                        op.truncate_to is not None):
                    continue
                # mutations staged after the delete recreate the object
            for w in op.writes:
                t.write(goid, w.offset, w.data)
            if op.truncate_to is not None:
                t.truncate(goid, op.truncate_to)
            sets = {k: v for k, v in op.attrs.items() if v is not None}
            if version is not None:
                # per-object version stamp (the reference's
                # object_info_t user_version in attr "_"): recovery
                # compares these across holders to find the
                # authoritative copy — epoch-first ordering makes an
                # interim primary's acked writes beat a revived
                # ex-primary's stale data
                sets["_v"] = \
                    f"{version.epoch}.{version.version}".encode()
            if sets:
                t.setattrs(goid, sets)
            for k in (k for k, v in op.attrs.items() if v is None):
                t.rmattr(goid, k)
            if op.omap_ops:
                t.touch(goid)   # omap mutation creates the object
            for mop in op.omap_ops:
                if mop[0] == "set":
                    t.omap_setkeys(goid, mop[1])
                elif mop[0] == "rm":
                    t.omap_rmkeys(goid, mop[1])
                elif mop[0] == "clear":
                    t.omap_clear(goid)
                elif mop[0] == "header":
                    t.omap_setheader(goid, mop[1])
        return t

    def read(self, oid: hobject_t, off: int = 0,
             length: int | None = None) -> np.ndarray:
        return self.replicas.local_read(oid, off, length)

    def stat(self, oid: hobject_t) -> int | None:
        return self.replicas.local_stat(oid)

    def submit_transaction(self, txn: PGTransaction, version: eversion_t,
                           on_commit: Callable[[], None]) -> None:
        store_txn = self._to_store_txn(txn, version)
        with self.lock:
            for oid, op in txn.ops.items():
                self.log.add(LogEntry(
                    version, oid,
                    LogOp.DELETE if op.delete else LogOp.MODIFY))
        n = self.replicas.n_replicas
        pending = {"count": n}

        def _on_commit(replica: int) -> None:
            with self.lock:
                pending["count"] -= 1
                if pending["count"] == 0:
                    self.log.roll_forward_to(version)
                    self.completed += 1
                    on_commit()

        for r in range(n):
            self.replicas.rep_write(r, store_txn, _on_commit)
