"""OSD: the distributed object-store core (reference src/osd/)."""
