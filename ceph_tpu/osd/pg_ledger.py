"""Control-plane flight recorder (docs/TRACING.md "Control plane").

The device plane attributes every launch (ops/profiler.py); this is
the same discipline applied to the OSD's *state machine*: every
peering / recovery / backfill transition of every led PG lands in a
bounded per-PG ring with a monotonic sequence number, every recovery
stage (peering round, wide scan, batched decode, shard push, throttle
wait) is timed into `lat_peering_*` / `lat_recovery_*` histograms on
the control-plane bucket axis, and the O(peers) costs ROADMAP item 4
names — remote collection listings per re-peered PG, objects scanned
vs objects actually recovered, throttle waits — are counted so the
superlinear fan-out term at 128-256 OSDs shows up as a measured curve
instead of folklore.

Re-expresses the reference's PeeringState event tracking (
`pg <id> query` state history + osd_pg_log scan accounting) and the
degraded-window bookkeeping behind `ceph health`'s PG_DEGRADED detail.

One ledger per OSD daemon — peering and recovery are per-daemon work,
so unlike the host-singleton device profiler there is no perf-owner
problem: every daemon registers its own perf set and ships its own
`ledger` block on MPGStats (mon/monitor.py consumes it for the
"since <ts>" degraded detail, the mgr progress module for completion
fractions).

Surfaces:
  - `pg ledger` asok (tools/ceph_cli.py daemon mode) — full dump
  - pgstats_block() — the MPGStats "ledger" block (cumulative,
    rounded, so the keepalive dedup in _pgstats_should_send still
    sees steady-state reports as unchanged)
  - blame_block() — the `recovery_blame` decomposition source for
    cluster_bench --scale rows
  - pg_state_counts() — per-pool state counts for the prometheus
    exporter's ceph_tpu_pg_state{state=...} gauges
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..common.perf_counters import (CONTROL_LAT_BUCKETS,
                                    PerfCountersBuilder)

# recovery stages the blame decomposition names (cluster_bench
# --scale `recovery_blame`): wall seconds spent in each per PG
STAGES = ("peering", "scan", "decode", "push", "throttle")

# counted O(peers) costs (ROADMAP item 4)
COUNTERS = ("remote_lists", "objects_scanned", "objects_recovered")


def _build_ledger_perf(name: str):
    b = (PerfCountersBuilder(name)
         .add_u64_counter("pg_transitions",
                          "PG state-machine transitions recorded")
         .add_u64_counter("pg_remote_lists",
                          "remote collection listings issued by "
                          "peering/recovery scans")
         .add_u64_counter("pg_objects_scanned",
                          "objects examined by recovery passes")
         .add_u64_counter("pg_objects_recovered",
                          "objects actually rebuilt/adopted/pushed")
         .add_u64_counter("pg_degraded_windows",
                          "degraded windows closed (PG returned to "
                          "full redundancy)")
         .add_u64_counter("pg_degraded_acked_writes",
                          "client writes acked while the PG served "
                          "below full redundancy (>= min_size)")
         .add_gauge("pg_degraded_open_windows",
                    "PGs currently inside an open degraded window")
         .add_histogram("lat_peering_total",
                        "wall seconds of one peering/reconcile round",
                        buckets=CONTROL_LAT_BUCKETS)
         .add_histogram("lat_recovery_scan",
                        "wall seconds of one recovery name-scan "
                        "(remote listings + filters)",
                        buckets=CONTROL_LAT_BUCKETS)
         .add_histogram("lat_recovery_decode",
                        "wall seconds of one batched "
                        "reconstruct-from-k pass",
                        buckets=CONTROL_LAT_BUCKETS)
         .add_histogram("lat_recovery_push",
                        "wall seconds of one rebuilt-shard push",
                        buckets=CONTROL_LAT_BUCKETS)
         .add_histogram("lat_recovery_throttle",
                        "wall seconds a recovery push spent in the "
                        "bandwidth throttle gate",
                        buckets=CONTROL_LAT_BUCKETS)
         .add_histogram("lat_degraded_window",
                        "wall seconds a degraded window stayed open",
                        buckets=CONTROL_LAT_BUCKETS))
    return b.create_perf_counters()


class _PGRecord:
    """Per-PG ledger state: the transition ring plus stage/counter
    accumulators.  Mutated under the GIL like perf counters — the
    hot-path writers are single attribute updates."""

    __slots__ = ("transitions", "state", "state_since", "last_seq",
                 "stage_s", "counters", "degraded_since",
                 "degraded_windows", "degraded_acked", "epoch")

    def __init__(self, ring: int):
        self.transitions: deque = deque(maxlen=ring)
        self.state = "new"
        self.state_since = time.time()
        self.last_seq = 0
        self.stage_s = dict.fromkeys(STAGES, 0.0)
        self.counters = dict.fromkeys(COUNTERS, 0)
        self.degraded_since: float | None = None
        self.degraded_windows = 0
        self.degraded_acked = 0
        self.epoch = 0

    def to_dict(self, last: int | None = None) -> dict:
        trans = list(self.transitions)
        if last is not None:
            trans = trans[-last:]
        d = {
            "state": self.state,
            "state_since": round(self.state_since, 3),
            "epoch": self.epoch,
            "stages_s": {k: round(v, 6)
                         for k, v in self.stage_s.items()},
            "counters": dict(self.counters),
            "degraded": {
                "open_since": (round(self.degraded_since, 3)
                               if self.degraded_since is not None
                               else None),
                "windows": self.degraded_windows,
                "acked_writes": self.degraded_acked,
            },
            "transitions": [
                {"seq": seq, "ts": round(ts, 3), "epoch": ep,
                 "from": frm, "to": to, "dur_s": round(dur, 6)}
                for seq, ts, ep, frm, to, dur in trans],
        }
        return d


class _Stage:
    """Times one recovery stage into the ledger (context manager)."""

    __slots__ = ("led", "pgid", "name", "t0")

    def __init__(self, led: "PGLedger", pgid, name: str):
        self.led = led
        self.pgid = pgid
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.led._add_stage(self.pgid, self.name,
                            time.perf_counter() - self.t0)
        return False


class _NullStage:
    """The ledger-off fast path: one shared instance, no state."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_STAGE = _NullStage()


class PGLedger:
    """Per-daemon PG state-machine ledger (module doc).  `enabled`
    gates every entry point on one attribute check; the off path
    allocates nothing (the NULL_TRACKED rule)."""

    def __init__(self, name: str = "pg_ledger", ring: int = 64,
                 perf=None):
        self.enabled = True
        self.ring = max(1, int(ring))
        self.perf = perf if perf is not None \
            else _build_ledger_perf(name)
        self._lock = threading.Lock()
        self._pgs: dict = {}          # pg_t -> _PGRecord
        self._seq = 0                 # daemon-wide monotonic sequence
        self._t0 = time.time()

    # -- record access ------------------------------------------------------

    def _rec(self, pgid) -> _PGRecord:
        rec = self._pgs.get(pgid)
        if rec is None:
            with self._lock:
                rec = self._pgs.get(pgid)
                if rec is None:
                    rec = _PGRecord(self.ring)
                    self._pgs[pgid] = rec
        return rec

    # -- hot-path entry points ----------------------------------------------

    def transition(self, pgid, state: str, epoch: int = 0) -> None:
        """One state-machine transition: timestamped ring entry with a
        daemon-wide monotonic seq; the time spent in the PREVIOUS
        state rides the entry (the reference's state-duration dump)."""
        if not self.enabled:
            return
        now = time.time()
        rec = self._rec(pgid)
        with self._lock:
            self._seq += 1
            seq = self._seq
        rec.transitions.append((seq, now, epoch, rec.state, state,
                                max(0.0, now - rec.state_since)))
        rec.state = state
        rec.state_since = now
        rec.last_seq = seq
        if epoch:
            rec.epoch = epoch
        self.perf.inc("pg_transitions")

    def stage(self, pgid, name: str):
        """Context manager timing one recovery stage (STAGES) for one
        PG; NULL_STAGE when the ledger is off."""
        if not self.enabled:
            return NULL_STAGE
        return _Stage(self, pgid, name)

    def _add_stage(self, pgid, name: str, dt: float) -> None:
        rec = self._rec(pgid)
        rec.stage_s[name] = rec.stage_s.get(name, 0.0) + dt
        key = "lat_peering_total" if name == "peering" \
            else f"lat_recovery_{name}"
        self.perf.hinc(key, dt)

    def count(self, pgid, key: str, n: int = 1) -> None:
        if not self.enabled:
            return
        rec = self._rec(pgid)
        rec.counters[key] = rec.counters.get(key, 0) + n
        self.perf.inc(f"pg_{key}", n)

    # -- degraded windows ---------------------------------------------------

    def degraded_open(self, pgid) -> None:
        """Open the PG's degraded window; idempotent while open."""
        if not self.enabled:
            return
        rec = self._rec(pgid)
        if rec.degraded_since is None:
            rec.degraded_since = time.time()
            self.perf.inc("pg_degraded_open_windows")

    def degraded_close(self, pgid) -> bool:
        """Close the PG's degraded window.  Returns True only for the
        close that actually ended an open window — callers may close
        redundantly (every clean recovery pass does), the window still
        closes exactly once."""
        if not self.enabled:
            return False
        rec = self._pgs.get(pgid)
        if rec is None or rec.degraded_since is None:
            return False
        dur = max(0.0, time.time() - rec.degraded_since)
        rec.degraded_since = None
        rec.degraded_windows += 1
        self.perf.inc("pg_degraded_windows")
        self.perf.inc("pg_degraded_open_windows", -1)
        self.perf.hinc("lat_degraded_window", dur)
        return True

    def degraded_ack(self, pgid) -> None:
        """One client write acked while the PG served below full
        redundancy (>= min_size, < size): the risk the degraded
        window exists to bound.  Opens the window when the write is
        the first degraded event seen for the PG."""
        if not self.enabled:
            return
        rec = self._rec(pgid)
        if rec.degraded_since is None:
            rec.degraded_since = time.time()
            self.perf.inc("pg_degraded_open_windows")
        rec.degraded_acked += 1
        self.perf.inc("pg_degraded_acked_writes")

    # -- aggregation surfaces -----------------------------------------------

    def totals(self) -> dict:
        """Daemon-wide cumulative stage seconds + counters."""
        with self._lock:
            recs = list(self._pgs.values())
        out = {f"{k}_s": 0.0 for k in STAGES}
        for k in COUNTERS:
            out[k] = 0
        out["transitions"] = 0
        out["degraded_windows"] = 0
        out["degraded_acked"] = 0
        open_since = []
        for rec in recs:
            for k in STAGES:
                out[f"{k}_s"] += rec.stage_s.get(k, 0.0)
            for k in COUNTERS:
                out[k] += rec.counters.get(k, 0)
            out["transitions"] += len(rec.transitions)
            out["degraded_windows"] += rec.degraded_windows
            out["degraded_acked"] += rec.degraded_acked
            if rec.degraded_since is not None:
                open_since.append(rec.degraded_since)
        out["degraded_open"] = len(open_since)
        out["degraded_oldest_since"] = (round(min(open_since), 3)
                                        if open_since else None)
        for k in STAGES:
            out[f"{k}_s"] = round(out[f"{k}_s"], 6)
        return out

    def pgstats_block(self) -> dict | None:
        """The MPGStats "ledger" block: cumulative totals, values
        rounded coarsely so a quiescent daemon's report stays
        bit-identical between stat windows and the keepalive dedup
        (_pgstats_should_send) keeps working.  None when the ledger
        has recorded nothing (boot-time reports stay lean)."""
        if not self.enabled:
            return None
        t = self.totals()
        if not t["transitions"] and not t["degraded_open"]:
            return None
        return {
            "peering_s": round(t["peering_s"], 2),
            "scan_s": round(t["scan_s"], 2),
            "decode_s": round(t["decode_s"], 2),
            "push_s": round(t["push_s"], 2),
            "throttle_s": round(t["throttle_s"], 2),
            "remote_lists": t["remote_lists"],
            "objects_scanned": t["objects_scanned"],
            "objects_recovered": t["objects_recovered"],
            "transitions": t["transitions"],
            "degraded_open": t["degraded_open"],
            "degraded_oldest_since": t["degraded_oldest_since"],
            "degraded_acked": t["degraded_acked"],
        }

    def blame_block(self) -> dict:
        """Cumulative decomposition source for cluster_bench --scale
        `recovery_blame` rows: callers snapshot before churn and diff
        after active+clean."""
        t = self.totals()
        return {k: t[k] for k in
                ("peering_s", "scan_s", "decode_s", "push_s",
                 "throttle_s", "remote_lists", "objects_scanned",
                 "objects_recovered", "transitions",
                 "degraded_windows", "degraded_acked")}

    def pg_state_counts(self) -> dict:
        """{pool_id: {state: count}} of current per-PG states — the
        exporter's ceph_tpu_pg_state{state=...} gauge source."""
        with self._lock:
            items = list(self._pgs.items())
        out: dict = {}
        for pgid, rec in items:
            pool = getattr(pgid, "pool", -1)
            pool_states = out.setdefault(pool, {})
            pool_states[rec.state] = pool_states.get(rec.state, 0) + 1
            if rec.degraded_since is not None:
                pool_states["degraded"] = \
                    pool_states.get("degraded", 0) + 1
        return out

    def dump(self, last: int | None = 8) -> dict:
        """The `pg ledger` asok payload."""
        with self._lock:
            items = sorted(self._pgs.items(), key=lambda kv: str(kv[0]))
        return {
            "enabled": self.enabled,
            "ring_size": self.ring,
            "uptime_s": round(time.time() - self._t0, 3),
            "totals": self.totals(),
            "latencies": self.perf.dump_latencies(),
            "pgs": {str(pgid): rec.to_dict(last)
                    for pgid, rec in items},
        }

    def reset(self) -> None:
        """Drop per-PG state (perf histograms stay monotonic, like
        the device profiler's reset)."""
        with self._lock:
            self._pgs.clear()
            self._seq = 0
            self._t0 = time.time()

    def set_ring_size(self, ring: int) -> None:
        self.ring = max(1, int(ring))
        with self._lock:
            for rec in self._pgs.values():
                rec.transitions = deque(rec.transitions,
                                        maxlen=self.ring)
