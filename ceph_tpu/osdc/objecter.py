"""Objecter: the client-side RADOS op state machine.

Re-expresses reference src/osdc/Objecter.{h,cc}: ops target a PG's
acting primary computed from the OSDMap via CRUSH *on the client*
(_calc_target, reference Objecter.cc:2759 -> OSDMap::pg_to_up_acting_osds),
are sent as MOSDOp and matched to MOSDOpReply by tid (op_submit :2256 /
_send_op :3216); every new map retargets and resends what's pending
(:1293).  Mon interaction (map subscription, admin commands) rides the
same engine, standing in for MonClient.
"""

from __future__ import annotations

import errno
import threading
import time

from ..common.tracked_op import OpTracker, TraceContext
from ..msg import Messenger
from ..msg import messages as M
from ..osd.osd_map import OSDMap, apply_inc_chain
from ..osd.types import hobject_t, spg_t


class TimedOut(Exception):
    pass


class Objecter:
    def __init__(self, mon_addr, name: str = "client", auth=None,
                 secure: bool = False, compress: str | None = None):
        self.auth = auth
        self.messenger = Messenger(name, auth=auth, secure=secure)
        self.messenger.compress_algo = compress
        self.messenger.add_dispatcher(self._dispatch)
        # op/command replies only wake waiter events — inline on the
        # reactor (reference ms_fast_dispatch).  Watch/notify events
        # run arbitrary user callbacks and stay on the executor.
        self.messenger.fast_dispatch = lambda msg: isinstance(
            msg, (M.MOSDOpReply, M.MMonCommandAck))
        # one (host, port) or a monmap-style list of them (reference
        # MonClient hunts across the monmap)
        from ..msg.addrs import normalize_mon_addrs
        self.mon_addrs = normalize_mon_addrs(mon_addr)
        self._mon_idx = 0
        self.mon_addr = self.mon_addrs[0]
        self.mon_conn = self.messenger.connect(self.mon_addrs[0])
        self.osdmap = OSDMap()
        self.map_event = threading.Event()
        self._map_nudge_pending = False
        self._tid = 0
        self._lock = threading.Lock()
        # client-side op tracking: every op gets the ROOT trace span
        # here (Dapper-style; the OSD continues the same span, shard
        # sub-ops branch children) — `dump_historic_ops` on this
        # tracker shows client-observed latency per op
        self.op_tracker = OpTracker(complaint_time=30.0)
        self._waiters: dict[int, dict] = {}
        self._mon_waiters: dict[int, dict] = {}
        self._auth_waiters: dict[int, dict] = {}
        # linger ops: cookie -> callback(oid_name, payload)
        # (reference linger_ops / watch support, Objecter.h)
        self._watch_cbs: dict[int, object] = {}
        self._next_cookie = 0
        # linger registrations: cookie -> {"pool", "name"} — the linger
        # thread re-asserts each on the current primary so a watch
        # survives its OSD's death/remap (reference Objecter.cc:1293
        # _scan_requests resending linger ops on every new map; here a
        # periodic check-and-rewatch replaces map-push-driven resend)
        self._lingers: dict[int, dict] = {}
        self.linger_interval = 5.0
        self._linger_stop = threading.Event()
        self._linger_thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        while self.osdmap.epoch == 0 and time.time() < deadline:
            self.mon_conn.send_message(M.MMonGetMap())
            if not self.map_event.wait(1.0):
                self._rotate_mon()
            self.map_event.clear()
        if self.osdmap.epoch == 0:
            raise TimedOut("no osdmap from mon")
        # cephx: trade our client key for a service ticket so OSD
        # connections can be authorized (reference MonClient
        # authenticate + CephxTicketManager)
        if self.auth is not None and self.auth.key is not None and \
                self.auth.ticket_blob is None:
            self._fetch_ticket()

    def _fetch_ticket(self, timeout: float = 5.0) -> None:
        import base64
        from ..auth import cephx
        with self._lock:
            self._tid += 1
            tid = self._tid
            w = {"event": threading.Event(), "reply": None}
            self._auth_waiters[tid] = w
        self.mon_conn.send_message(M.MAuth(self.auth.entity, tid))
        if not w["event"].wait(timeout):
            raise TimedOut("no auth reply from mon")
        reply = w["reply"]
        if reply.result != 0:
            raise PermissionError(
                f"mon refused ticket: errno {-reply.result}")
        sealed = cephx.unseal(self.auth.key, reply.sealed_key)
        self.auth.set_ticket(
            reply.ticket, base64.b64decode(sealed["session_key"]),
            float(sealed.get("expires", 0.0)))

    def _rotate_mon(self) -> None:
        """Hunt to the next monitor (reference MonClient::_reopen_session
        rotation when the current mon stops answering)."""
        if len(self.mon_addrs) == 1:
            return
        self._mon_idx = (self._mon_idx + 1) % len(self.mon_addrs)
        self.mon_addr = self.mon_addrs[self._mon_idx]
        self.mon_conn = self.messenger.connect(self.mon_addr)

    def shutdown(self) -> None:
        self._linger_stop.set()
        self.messenger.shutdown()

    # -- dispatch -----------------------------------------------------------

    def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, M.MMonMap):
            newmap = OSDMap.from_json(msg.map_json)
            # multiple mons publish to us after rotation; a slower
            # mon's older epoch must not regress the map
            if newmap.epoch >= self.osdmap.epoch:
                self.osdmap = newmap
            self._map_nudge_pending = False
            self.map_event.set()
        elif isinstance(msg, M.MOSDMapInc):
            # incremental publish / keepalive ack: apply the delta
            # chain like the OSD does; a gap (or a keepalive claiming
            # an epoch we never got) re-requests a full map
            m = apply_inc_chain(self.osdmap, msg.incs)
            if m is None or (not msg.incs and
                             msg.epoch > self.osdmap.epoch):
                try:
                    self.mon_conn.send_message(M.MMonGetMap())
                except Exception:  # noqa: BLE001 - mon electing
                    pass
                return
            self.osdmap = m
            self._map_nudge_pending = False
            self.map_event.set()
        elif isinstance(msg, M.MOSDOpReply):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()
        elif isinstance(msg, M.MMonCommandAck):
            with self._lock:
                w = self._mon_waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()
        elif isinstance(msg, M.MAuthReply):
            with self._lock:
                w = self._auth_waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()
        elif isinstance(msg, M.MWatchNotify) and not msg.is_ack:
            cb = self._watch_cbs.get(msg.cookie)
            if cb is not None:
                try:
                    cb(msg.oid.name, msg.payload)
                finally:
                    conn.send_message(M.MWatchNotify(
                        msg.oid, msg.notify_id, msg.cookie, b"",
                        is_ack=True))

    # -- map plumbing -------------------------------------------------------

    def refresh_map(self, timeout: float = 5.0) -> None:
        # carry our epoch: a current map earns a keepalive ack, a
        # stale one an incremental chain — not a full payload per
        # refresh (docs/ARCHITECTURE.md "Map distribution")
        self.map_event.clear()
        self.mon_conn.send_message(
            M.MMonGetMap(have_epoch=self.osdmap.epoch))
        if not self.map_event.wait(timeout):
            self._rotate_mon()
            self.mon_conn.send_message(
                M.MMonGetMap(have_epoch=self.osdmap.epoch))
            self.map_event.wait(timeout)

    def _calc_target(self, pool_id: int, name: str
                     ) -> tuple[spg_t, int] | None:
        """reference _calc_target: object -> pg -> acting primary."""
        pgid = self.osdmap.object_to_pg(pool_id, name)
        spg = self.osdmap.primary_shard(pgid)
        if spg is None:
            return None
        _, _, _, primary = self.osdmap.pg_to_up_acting_osds(pgid)
        return spg, primary

    # -- op submission ------------------------------------------------------

    def op_submit(self, pool_id: int, name: str, ops: list,
                  data: bytes = b"", timeout: float = 30.0,
                  attempts: int = 3, snap: int = 0,
                  snapc: list | None = None,
                  qos_class: str | None = None) -> M.MOSDOpReply:
        # an expired ticket would make every OSD reconnect fail
        # permanently; refresh before it lapses (reference
        # CephxTicketManager renewal)
        if self.auth is not None and self.auth.key is not None and \
                not self.auth.ticket_valid():
            try:
                self._fetch_ticket()
            except Exception:  # noqa: BLE001 - mon may be electing
                pass
        oid = hobject_t(pool=pool_id, name=name, snap=snap)
        # root trace span: origin_ts stamps "objecter submit" on every
        # downstream timeline of this request
        trace = TraceContext.new()
        top = self.op_tracker.create(
            "osd_op", f"{pool_id}/{name} {[op[0] for op in ops]}",
            trace)
        try:
            return self._op_submit_attempts(
                pool_id, name, ops, data, timeout, attempts, snapc,
                oid, trace, top, qos_class)
        finally:
            # idempotent (reply/timeout paths unregister with their
            # result); catches exceptions escaping the retry loop —
            # e.g. connect() to a dead primary — that would otherwise
            # leak the op in the tracker forever
            self.op_tracker.unregister(top, -errno.EIO)

    def _op_submit_attempts(self, pool_id, name, ops, data, timeout,
                            attempts, snapc, oid, trace, top,
                            qos_class=None) -> M.MOSDOpReply:
        last_err = None
        # EAGAIN (not-primary / peering-incomplete) replies arrive in
        # milliseconds now that the OSD fences every op path; they ride
        # a short backoff BUDGET instead of the attempt counter, or a
        # 2s peering blip would burn all attempts instantly (reference:
        # client op backoff, RECOVERY_WAIT).  Anchored at the FIRST
        # EAGAIN (not op entry — a slow first attempt must not eat the
        # budget) and bounded well below the op timeout: a PG that
        # CANNOT peer (too many shards down) must fail fast, not pin
        # the caller for the whole op budget
        deadline = None
        attempt = 0
        while attempt < attempts:
            tgt = self._calc_target(pool_id, name)
            if tgt is None:
                self.refresh_map()
                last_err = -errno.EHOSTUNREACH
                attempt += 1
                continue
            spg, primary = tgt
            info = self.osdmap.osds.get(primary)
            if info is None or info.addr is None:
                self.refresh_map()
                last_err = -errno.EHOSTUNREACH
                attempt += 1
                continue
            with self._lock:
                self._tid += 1
                tid = self._tid
                w = {"event": threading.Event(), "reply": None}
                self._waiters[tid] = w
            conn = self.messenger.connect(tuple(info.addr))
            conn.send_message(M.MOSDOp(spg, oid, ops, data, tid,
                                       self.osdmap.epoch, snapc=snapc,
                                       trace=trace.to_wire(),
                                       qos=qos_class))
            if w["event"].wait(timeout):
                reply = w["reply"]
                if reply.epoch > self.osdmap.epoch and \
                        not self._map_nudge_pending:
                    # the OSD is on a newer map (e.g. a pool's pg_num
                    # grew and our target PG split): nudge a refresh so
                    # subsequent ops retarget to the children without
                    # having to eat an EAGAIN first.  One nudge per
                    # staleness window — a burst of stale replies must
                    # not multiply into a burst of mon requests.
                    self._map_nudge_pending = True
                    try:
                        self.mon_conn.send_message(M.MMonGetMap(
                            have_epoch=self.osdmap.epoch))
                    except Exception:  # noqa: BLE001 - mon electing
                        pass
                if reply.result == -errno.EAGAIN:
                    # primary moved or PG still peering: retarget
                    top.mark_event("retry")
                    self.refresh_map()
                    last_err = reply.result
                    if deadline is None:
                        deadline = time.time() + min(timeout, 5.0)
                    if time.time() >= deadline:
                        attempt += 1    # budget exhausted — the
                        # retarget fast-path below must not bypass it
                        # (sustained map churn would spin forever)
                    elif self._calc_target(pool_id, name) != tgt:
                        # the refreshed map moved the op — a pg_num
                        # change (split/merge) or primary remap, not a
                        # peering blip: go straight at the new target
                        # instead of eating the flat backoff
                        pass
                    else:
                        time.sleep(0.25)
                    continue
                top.mark_event("reply")
                self.op_tracker.unregister(top, reply.result)
                return reply
            with self._lock:
                self._waiters.pop(tid, None)
            top.mark_event("attempt_timeout")
            self.refresh_map()
            last_err = -errno.ETIMEDOUT
            attempt += 1
        top.mark_event("timeout")
        self.op_tracker.unregister(top, last_err)
        raise TimedOut(f"op {name} failed after {attempts} attempts "
                       f"(last {last_err})")

    # -- watch/notify -------------------------------------------------------

    def watch(self, pool_id: int, name: str, callback) -> int:
        """Register a watch; returns the cookie (reference
        IoCtxImpl::watch via linger ops)."""
        # globally unique cookie: per-client counters collide across
        # processes (two fresh clients would both register cookie 1 on
        # one object, clobbering each other's watch — fatal for
        # watcher-liveness protocols like the RBD exclusive lock)
        import os as _os
        with self._lock:
            cookie = int.from_bytes(_os.urandom(8), "little") | 1
            while cookie in self._watch_cbs:
                cookie = int.from_bytes(_os.urandom(8), "little") | 1
            self._watch_cbs[cookie] = callback
        self.op_submit(pool_id, name, [["watch", cookie]])
        with self._lock:
            self._lingers[cookie] = {"pool": pool_id, "name": name}
        self._ensure_linger_thread()
        return cookie

    def unwatch(self, pool_id: int, name: str, cookie: int) -> None:
        # pop BEFORE the op: a linger tick that starts after this point
        # sees the cookie gone and skips; a tick already mid-flight is
        # compensated by its own post-rewatch membership re-check (see
        # _linger_loop) — so no lock is held across a blocking op
        with self._lock:
            self._lingers.pop(cookie, None)
        self.op_submit(pool_id, name, [["unwatch", cookie]])
        self._watch_cbs.pop(cookie, None)

    def _ensure_linger_thread(self) -> None:
        with self._lock:
            if self._linger_thread is not None and \
                    self._linger_thread.is_alive():
                return
            self._linger_thread = threading.Thread(
                target=self._linger_loop, daemon=True,
                name="objecter-linger")
            self._linger_thread.start()

    def _linger_loop(self) -> None:
        """Keep every registered watch alive across OSD death, revive,
        and PG remap.  Each tick: refresh the map, then verify (via
        listwatchers, a cheap read on the primary) that our cookie is
        still registered — a fresh primary or a restarted OSD has an
        empty watcher table — and re-send the watch op if not.  The
        reference drives this from map pushes + per-watch ping timers
        (Objecter::_linger_ops_resend, WatchNotify ping); a periodic
        check-and-rewatch gives the same guarantee without a mon-push
        subscription."""
        import json as _json
        while not self._linger_stop.wait(self.linger_interval):
            with self._lock:
                regs = dict(self._lingers)
            if not regs:
                continue
            try:
                self.refresh_map(timeout=2.0)
            except Exception:  # noqa: BLE001 - mon electing: next tick
                pass
            for cookie, reg in regs.items():
                with self._lock:
                    if cookie not in self._lingers:
                        continue         # unwatched meanwhile
                try:
                    reply = self.op_submit(
                        reg["pool"], reg["name"], [["listwatchers"]],
                        timeout=5.0, attempts=1)
                    live = _json.loads(bytes(reply.data).decode()) \
                        if reply.result == 0 else []
                    if cookie not in live:
                        self.op_submit(
                            reg["pool"], reg["name"],
                            [["watch", cookie]], timeout=5.0,
                            attempts=1)
                        # compensate the unwatch race: if the app
                        # unwatched while we were re-asserting, undo —
                        # otherwise the orphan cookie would eat every
                        # future notify's ack wait
                        with self._lock:
                            still = cookie in self._lingers
                        if not still:
                            self.op_submit(
                                reg["pool"], reg["name"],
                                [["unwatch", cookie]], timeout=5.0,
                                attempts=1)
                except Exception:  # noqa: BLE001 - OSD still down:
                    continue           # re-check next tick

    def notify(self, pool_id: int, name: str, payload: bytes) -> None:
        self.op_submit(pool_id, name, [["notify", len(payload)]],
                       bytes(payload))

    # -- mon commands -------------------------------------------------------

    def mon_command(self, cmd: dict, timeout: float = 15.0
                    ) -> tuple[int, dict]:
        """Admin command with mon failover: a dead or quorum-less mon
        rotates the session to the next one (reference MonClient
        hunting + command resend on session reset)."""
        deadline = time.time() + timeout
        # the attempt window scales with the caller's budget: a SLOW
        # (not dead) mon whose ack RT exceeds a fixed 3 s window would
        # never land an ack — every resend starts a new tid, and the
        # resend storm itself adds mon load.  Short budgets keep the
        # snappy 3 s hunt; long budgets wait the mon out.
        attempt_timeout = min(max(3.0, timeout / 3.0), timeout)
        while True:
            with self._lock:
                self._tid += 1
                tid = self._tid
                w = {"event": threading.Event(), "reply": None}
                self._mon_waiters[tid] = w
            self.mon_conn.send_message(M.MMonCommand(cmd, tid))
            if w["event"].wait(attempt_timeout):
                ack = w["reply"]
                if ack.result == -errno.EAGAIN and \
                        time.time() < deadline:
                    # electing / quorum-less mon: another mon may have a
                    # working leader — rotate before retrying
                    self._rotate_mon()
                    time.sleep(0.3)
                    continue
                return ack.result, ack.out
            with self._lock:
                self._mon_waiters.pop(tid, None)
            if time.time() >= deadline:
                raise TimedOut(f"mon command {cmd.get('prefix')}")
            self._rotate_mon()
