"""Client-side op engine (reference src/osdc/)."""

from .objecter import Objecter

__all__ = ["Objecter"]
