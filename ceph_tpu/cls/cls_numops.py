"""cls_numops: server-side numeric ops on object bytes (reference
src/cls/numops/: add/sub/mul on values stored in the object)."""

from __future__ import annotations

import errno
import json

from . import ClsContext, ClsError, register_class


def _value(ctx: ClsContext) -> float:
    raw = ctx.read()
    if not raw:
        return 0.0
    try:
        return float(raw.decode())
    except ValueError:
        raise ClsError(errno.EINVAL, "object does not hold a number")


def _apply(ctx: ClsContext, inp: bytes, op) -> bytes:
    req = json.loads(inp.decode())
    out = op(_value(ctx), float(req["value"]))
    if out == int(out):
        out = int(out)
    ctx.write_full(str(out).encode())
    return str(out).encode()


register_class("numops", {
    "add": lambda ctx, inp: _apply(ctx, inp, lambda a, b: a + b),
    "sub": lambda ctx, inp: _apply(ctx, inp, lambda a, b: a - b),
    "mul": lambda ctx, inp: _apply(ctx, inp, lambda a, b: a * b),
})
