"""cls_user-role: per-user account object class.

Re-expresses the slice of reference src/cls/user/cls_user.cc RGW
consumes: a user header object holding per-bucket usage stats
(entries, bytes) updated server-side as bucket indexes change, plus
quota fields — the data the reference's RGWQuotaHandler reads before
admitting writes (src/rgw/rgw_quota.cc).

Layout: {"buckets": {bucket: {"objects": int, "bytes": int}},
"quota": {"max_objects": int|-1, "max_bytes": int|-1},
"pending": {token: {"objects": int, "bytes": int, "ts": float}}}.

The "pending" map backs reserve/release: quota admission is a
server-side reservation in the SAME atomic class call that checks the
totals, so two writers racing the last quota slot — from any process
or host — serialize on the user object and exactly one wins (the
reference serializes admission in RGWQuotaHandler against cached
stats; here the OSD's per-object CALL serialization is the lock).
Reservations carry a TTL so a crashed writer's reservation expires
instead of leaking quota."""

from __future__ import annotations

import errno
import json
import time
import uuid

from . import ClsError, register_class


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {"buckets": {}, "quota": {"max_objects": -1,
                                         "max_bytes": -1}}
    try:
        return json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt user object: {e}") from e


def _store(ctx, d: dict) -> None:
    ctx.write_full(json.dumps(d, separators=(",", ":")).encode())


def add_stats(ctx, inp: bytes) -> bytes:
    """input: {"bucket": str, "objects": +/-int, "bytes": +/-int} —
    atomic server-side delta (reference cls_user_add_bucket /
    cls_user_update_buckets)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    b = d["buckets"].setdefault(req["bucket"],
                                {"objects": 0, "bytes": 0})
    b["objects"] = max(0, b["objects"] + int(req.get("objects", 0)))
    b["bytes"] = max(0, b["bytes"] + int(req.get("bytes", 0)))
    _store(ctx, d)
    return b""


def rm_bucket(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    d["buckets"].pop(req["bucket"], None)
    _store(ctx, d)
    return b""


def get_header(ctx, _inp: bytes) -> bytes:
    """-> the whole user record incl. totals."""
    d = _load(ctx)
    totals = {"objects": sum(b["objects"]
                             for b in d["buckets"].values()),
              "bytes": sum(b["bytes"] for b in d["buckets"].values())}
    return json.dumps({**d, "totals": totals}).encode()


def set_quota(ctx, inp: bytes) -> bytes:
    """input: {"max_objects": int|-1, "max_bytes": int|-1} (-1 =
    unlimited)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    for k in ("max_objects", "max_bytes"):
        if k in req:
            d["quota"][k] = int(req[k])
    _store(ctx, d)
    return b""


def _purge_pending(d: dict, now: float, ttl: float) -> None:
    pend = d.get("pending")
    if not pend:
        return
    dead = [t for t, p in pend.items()
            if now - float(p.get("ts", 0.0)) > ttl]
    for t in dead:
        del pend[t]
    if not pend:
        d.pop("pending", None)


def reserve(ctx, inp: bytes) -> bytes:
    """input: {"objects": +/-int, "bytes": +/-int, "ttl": float} —
    check quota against committed totals PLUS live reservations and,
    if it fits, record a reservation; -> {"token": str}.  Raises
    EDQUOT when the delta would exceed either limit.  Negative deltas
    (shrinking overwrite, delete) always admit — freeing space must
    never be blocked by quota."""
    req = json.loads(inp.decode())
    d_obj = int(req.get("objects", 0))
    d_bytes = int(req.get("bytes", 0))
    ttl = float(req.get("ttl", 30.0))
    d = _load(ctx)
    now = time.time()
    _purge_pending(d, now, ttl)
    if d_obj > 0 or d_bytes > 0:
        q = d.get("quota", {})
        max_o = int(q.get("max_objects", -1))
        max_b = int(q.get("max_bytes", -1))
        pend = d.get("pending", {})
        cur_o = (sum(b["objects"] for b in d["buckets"].values())
                 + sum(int(p.get("objects", 0)) for p in pend.values()))
        cur_b = (sum(b["bytes"] for b in d["buckets"].values())
                 + sum(int(p.get("bytes", 0)) for p in pend.values()))
        if max_o >= 0 and d_obj > 0 and cur_o + d_obj > max_o:
            raise ClsError(errno.EDQUOT, "object quota exceeded")
        if max_b >= 0 and d_bytes > 0 and cur_b + d_bytes > max_b:
            raise ClsError(errno.EDQUOT, "byte quota exceeded")
    token = uuid.uuid4().hex
    d.setdefault("pending", {})[token] = {
        "objects": d_obj, "bytes": d_bytes, "ts": now}
    _store(ctx, d)
    return json.dumps({"token": token}).encode()


def release(ctx, inp: bytes) -> bytes:
    """input: {"token": str} — drop a reservation (the write either
    committed its real delta via add_stats or aborted).  Unknown
    tokens are fine: the reservation may have TTL-expired."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    pend = d.get("pending")
    if pend and pend.pop(req.get("token", ""), None) is not None:
        if not pend:
            d.pop("pending", None)
        _store(ctx, d)
    return b""


register_class("user", {
    "add_stats": add_stats,
    "rm_bucket": rm_bucket,
    "get_header": get_header,
    "set_quota": set_quota,
    "reserve": reserve,
    "release": release,
})
