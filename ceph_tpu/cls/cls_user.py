"""cls_user-role: per-user account object class.

Re-expresses the slice of reference src/cls/user/cls_user.cc RGW
consumes: a user header object holding per-bucket usage stats
(entries, bytes) updated server-side as bucket indexes change, plus
quota fields — the data the reference's RGWQuotaHandler reads before
admitting writes (src/rgw/rgw_quota.cc).

Layout: {"buckets": {bucket: {"objects": int, "bytes": int}},
"quota": {"max_objects": int|-1, "max_bytes": int|-1}}.
"""

from __future__ import annotations

import json

from . import ClsError, register_class


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {"buckets": {}, "quota": {"max_objects": -1,
                                         "max_bytes": -1}}
    try:
        return json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt user object: {e}") from e


def _store(ctx, d: dict) -> None:
    ctx.write_full(json.dumps(d, separators=(",", ":")).encode())


def add_stats(ctx, inp: bytes) -> bytes:
    """input: {"bucket": str, "objects": +/-int, "bytes": +/-int} —
    atomic server-side delta (reference cls_user_add_bucket /
    cls_user_update_buckets)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    b = d["buckets"].setdefault(req["bucket"],
                                {"objects": 0, "bytes": 0})
    b["objects"] = max(0, b["objects"] + int(req.get("objects", 0)))
    b["bytes"] = max(0, b["bytes"] + int(req.get("bytes", 0)))
    _store(ctx, d)
    return b""


def rm_bucket(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    d["buckets"].pop(req["bucket"], None)
    _store(ctx, d)
    return b""


def get_header(ctx, _inp: bytes) -> bytes:
    """-> the whole user record incl. totals."""
    d = _load(ctx)
    totals = {"objects": sum(b["objects"]
                             for b in d["buckets"].values()),
              "bytes": sum(b["bytes"] for b in d["buckets"].values())}
    return json.dumps({**d, "totals": totals}).encode()


def set_quota(ctx, inp: bytes) -> bytes:
    """input: {"max_objects": int|-1, "max_bytes": int|-1} (-1 =
    unlimited)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    for k in ("max_objects", "max_bytes"):
        if k in req:
            d["quota"][k] = int(req[k])
    _store(ctx, d)
    return b""


register_class("user", {
    "add_stats": add_stats,
    "rm_bucket": rm_bucket,
    "get_header": get_header,
    "set_quota": set_quota,
})
