"""cls_journal-role: ordered, trimmable event log object class.

Re-expresses the slice of reference src/cls/journal/cls_journal.cc the
framework's log consumers need: a journal header object the OSD mutates
server-side, so appends allocate sequence numbers atomically, clients
(replayers/mirrors) register commit positions on the journal itself,
and trim is fenced by the slowest registered client (reference
cls::journal::client::committed + set_minimum_set).

Consumers: the RGW multisite mod-log (rgw/sync.py) and the RBD image
journal (rbd/journal.py) — the same seam the reference routes both
through.

Layout (one JSON doc in the object body, like the other cls modules —
see cls_rgw.py's idiomatic-shift note): {"next": int, "entries":
{"%016x": entry}, "clients": {id: pos}}.
"""

from __future__ import annotations

import json

from . import ClsError, register_class


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {"next": 0, "entries": {}, "clients": {}}
    try:
        return json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt journal: {e}") from e


def _store(ctx, d: dict) -> None:
    ctx.write_full(json.dumps(d, separators=(",", ":")).encode())


def create(ctx, _inp: bytes) -> bytes:
    if not ctx.read():
        _store(ctx, {"next": 0, "entries": {}, "clients": {}})
    return b""


def append(ctx, inp: bytes) -> bytes:
    """input: {"entry": {...}} -> seq (decimal).  Seq allocation and
    entry store are one server-side mutation: concurrent writers can
    never collide (reference cls_journal guard_append/append)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    seq = int(d["next"])
    d["entries"][f"{seq:016x}"] = req["entry"]
    d["next"] = seq + 1
    _store(ctx, d)
    return str(seq).encode()


def list_entries(ctx, inp: bytes) -> bytes:
    """input: {"after_seq": int, "max": int} -> {"entries":
    [[seq, entry]...], "truncated": bool} in seq order."""
    req = json.loads(inp.decode()) if inp else {}
    after = int(req.get("after_seq", -1))
    limit = int(req.get("max", 256))
    d = _load(ctx)
    keys = sorted(k for k in d["entries"] if int(k, 16) > after)
    out = [[int(k, 16), d["entries"][k]] for k in keys[:limit]]
    return json.dumps({"entries": out,
                       "truncated": len(keys) > limit}).encode()


def client_register(ctx, inp: bytes) -> bytes:
    """input: {"id": str, "pos": int} — idempotent; an existing
    client keeps its position (a restarted replayer must resume, not
    reset)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    d["clients"].setdefault(req["id"], int(req.get("pos", -1)))
    _store(ctx, d)
    return b""


def client_update(ctx, inp: bytes) -> bytes:
    """input: {"id": str, "pos": int} — commit position only moves
    forward (an old in-flight update must not rewind a newer one)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    if req["id"] not in d["clients"]:
        raise ClsError(2, f"no such client {req['id']!r}")
    d["clients"][req["id"]] = max(int(d["clients"][req["id"]]),
                                  int(req["pos"]))
    _store(ctx, d)
    return b""


def client_get(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    if req["id"] not in d["clients"]:
        raise ClsError(2, f"no such client {req['id']!r}")
    return json.dumps({"pos": d["clients"][req["id"]]}).encode()


def client_list(ctx, _inp: bytes) -> bytes:
    return json.dumps(_load(ctx)["clients"]).encode()


def client_unregister(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    d["clients"].pop(req["id"], None)
    _store(ctx, d)
    return b""


def trim(ctx, inp: bytes) -> bytes:
    """input: {"to_seq": int} — drop entries <= to_seq.  Fenced by the
    slowest registered client: trimming past an unconsumed entry is
    refused (reference set_minimum_set fencing)."""
    req = json.loads(inp.decode())
    to_seq = int(req["to_seq"])
    d = _load(ctx)
    if d["clients"]:
        floor = min(int(p) for p in d["clients"].values())
        if to_seq > floor:
            raise ClsError(22, f"trim {to_seq} past slowest client "
                               f"position {floor}")
    d["entries"] = {k: v for k, v in d["entries"].items()
                    if int(k, 16) > to_seq}
    _store(ctx, d)
    return b""


register_class("journal", {
    "create": create,
    "append": append,
    "list": list_entries,
    "client_register": client_register,
    "client_update": client_update,
    "client_get": client_get,
    "client_list": client_list,
    "client_unregister": client_unregister,
    "trim": trim,
})
