"""cls_refcount: tag-based refcounting (reference src/cls/refcount/):
get/put named refs on an object; drop to zero -> caller may delete."""

from __future__ import annotations

import json

from . import ClsContext, register_class

ATTR = "cls_refcount.refs"


def _load(ctx: ClsContext) -> list:
    raw = ctx.getxattr(ATTR)
    return json.loads(raw.decode()) if raw else []


def get(ctx: ClsContext, inp: bytes) -> bytes:
    tag = json.loads(inp.decode())["tag"]
    refs = _load(ctx)
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(ATTR, json.dumps(refs).encode())
    return json.dumps({"refs": refs}).encode()


def put(ctx: ClsContext, inp: bytes) -> bytes:
    tag = json.loads(inp.decode())["tag"]
    refs = _load(ctx)
    if tag in refs:
        refs.remove(tag)
    ctx.setxattr(ATTR, json.dumps(refs).encode())
    return json.dumps({"refs": refs}).encode()


def read(ctx: ClsContext, inp: bytes) -> bytes:
    return json.dumps({"refs": _load(ctx)}).encode()


register_class("refcount", {"get": get, "put": put, "read": read})
