"""cls_lock: advisory object locks (reference src/cls/lock/).

Lock state lives in a JSON xattr; methods: lock (exclusive|shared),
unlock, break_lock, get_info.  Input/output are JSON bytes.
"""

from __future__ import annotations

import errno
import json

from . import ClsContext, ClsError, register_class

ATTR = "cls_lock.state"


def _load(ctx: ClsContext) -> dict:
    raw = ctx.getxattr(ATTR)
    return json.loads(raw.decode()) if raw else {"lockers": {},
                                                 "type": None}


def _store(ctx: ClsContext, st: dict) -> None:
    ctx.setxattr(ATTR, json.dumps(st).encode())


def lock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    name, owner = req["name"], req["owner"]
    ltype = req.get("type", "exclusive")
    st = _load(ctx)
    lockers = st["lockers"]
    if lockers:
        if st["type"] == "exclusive" or ltype == "exclusive":
            if owner not in lockers:
                raise ClsError(errno.EBUSY, "locked")
    # the locker's messenger entity rides the record (reference
    # cls_lock stores the locker's addr/cookie) so a steal can
    # blacklist the old owner at the OSDs before breaking the lock
    lockers[owner] = {"name": name, "type": ltype,
                      "entity": req.get("entity")}
    st["type"] = ltype
    _store(ctx, st)
    return b"{}"


def unlock(ctx: ClsContext, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    st = _load(ctx)
    if req["owner"] not in st["lockers"]:
        raise ClsError(errno.ENOENT, "not locked by owner")
    del st["lockers"][req["owner"]]
    if not st["lockers"]:
        st["type"] = None
    _store(ctx, st)
    return b"{}"


def break_lock(ctx: ClsContext, inp: bytes) -> bytes:
    st = _load(ctx)
    st["lockers"] = {}
    st["type"] = None
    _store(ctx, st)
    return b"{}"


def get_info(ctx: ClsContext, inp: bytes) -> bytes:
    return json.dumps(_load(ctx)).encode()


register_class("lock", {
    "lock": lock, "unlock": unlock,
    "break_lock": break_lock, "get_info": get_info,
})
