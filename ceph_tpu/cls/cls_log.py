"""cls_log-role: timestamped log object class.

Re-expresses the slice of reference src/cls/log/cls_log.cc its in-repo
consumer needs (the RGW usage/ops log, reference rgw_usage.cc rides
cls_log the same way): server-side appends keyed by timestamp+counter,
time-range listing with pagination, and time-bounded trim.

Layout (one JSON doc in the body, like the other cls modules):
{"next": int, "entries": {"%016.6f_%08d": entry}}.
"""

from __future__ import annotations

import json

from . import ClsError, register_class


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {"next": 0, "entries": {}}
    try:
        return json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt log object: {e}") from e


def _store(ctx, d: dict) -> None:
    ctx.write_full(json.dumps(d, separators=(",", ":")).encode())


def _key(ts: float, n: int) -> str:
    return f"{ts:016.6f}_{n:08d}"


def add(ctx, inp: bytes) -> bytes:
    """input: {"ts": float, "entry": {...}} (or a list under
    "entries").  Key = timestamp + server-side counter: same-timestamp
    appends never collide (reference cls_log add with sub-second
    uniquifier)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    ents = req.get("entries")
    if ents is None:
        ents = [{"ts": req["ts"], "entry": req["entry"]}]
    for e in ents:
        n = int(d["next"])
        d["entries"][_key(float(e["ts"]), n)] = e["entry"]
        d["next"] = n + 1
    _store(ctx, d)
    return b""


def list_entries(ctx, inp: bytes) -> bytes:
    """input: {"from_ts": float, "to_ts": float, "marker": str,
    "max": int} -> {"entries": [[key, ts, entry]...], "truncated":
    bool, "marker": str} in time order."""
    req = json.loads(inp.decode()) if inp else {}
    from_ts = float(req.get("from_ts", 0.0))
    to_ts = float(req.get("to_ts", 1e18))
    marker = req.get("marker", "")
    limit = int(req.get("max", 256))
    d = _load(ctx)
    keys = sorted(k for k in d["entries"]
                  if k > marker and
                  from_ts <= float(k.split("_")[0]) < to_ts)
    page = keys[:limit]
    return json.dumps({
        "entries": [[k, float(k.split("_")[0]), d["entries"][k]]
                    for k in page],
        "truncated": len(keys) > limit,
        "marker": page[-1] if page else marker}).encode()


def trim(ctx, inp: bytes) -> bytes:
    """input: {"to_ts": float} — drop entries with ts < to_ts."""
    req = json.loads(inp.decode())
    to_ts = float(req["to_ts"])
    d = _load(ctx)
    d["entries"] = {k: v for k, v in d["entries"].items()
                    if float(k.split("_")[0]) >= to_ts}
    _store(ctx, d)
    return b""


register_class("log", {
    "add": add,
    "list": list_entries,
    "trim": trim,
})
