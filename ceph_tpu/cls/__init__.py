"""Object classes: server-side compute on objects (reference
src/objclass/, src/cls/, osd/ClassHandler.{h,cc}).

The reference dlopens cls_*.so plugins into the OSD and dispatches
CEPH_OSD_OP_CALL from do_osd_ops (PrimaryLogPG.cc:5643) into their
registered methods.  Here classes are python modules registered with
`register_class`; a method is fn(ctx, input: bytes) -> bytes (raising
ClsError(errno) to fail the op).  The ctx exposes the object the op
targets — read, write, xattrs — through the owning PG backend, so class
methods compose with EC pools exactly like client I/O does.

Built-ins: `lock` (advisory locks, reference cls_lock), `numops`
(atomic u64 arithmetic, reference cls_numops), `refcount`
(reference cls_refcount).
"""

from __future__ import annotations

import errno
from typing import Callable

Method = Callable[["ClsContext", bytes], bytes]


class ClsError(Exception):
    def __init__(self, err: int, msg: str = ""):
        super().__init__(msg or errno.errorcode.get(err, str(err)))
        self.errno = err


_CLASSES: dict[str, dict[str, Method]] = {}


def register_class(name: str, methods: dict[str, Method]) -> None:
    _CLASSES[name] = dict(methods)


def get_method(cls_name: str, method: str) -> Method | None:
    return _CLASSES.get(cls_name, {}).get(method)


def list_classes() -> dict[str, list[str]]:
    return {c: sorted(m) for c, m in _CLASSES.items()}


class ClsContext:
    """Execution context handed to class methods (reference cls_method
    call context + cls_cxx_read/write/getxattr/setxattr)."""

    def __init__(self, daemon, state, pgid, oid):
        self.daemon = daemon
        self.state = state
        self.pgid = pgid
        self.oid = oid
        self._pending_attrs: dict[str, bytes | None] = {}
        self._pending_write: tuple[int, bytes] | None = None

    # -- reads --------------------------------------------------------------

    def read(self, off: int = 0, length: int | None = None) -> bytes:
        import numpy as np
        be = self.state.backend
        data = be.read(self.oid, off, length)
        return np.asarray(data).tobytes() if data is not None else b""

    def getxattr(self, name: str) -> bytes | None:
        if name in self._pending_attrs:
            return self._pending_attrs[name]
        if self.state.kind == "ec":
            be = self.state.backend
            for s in range(be.n):
                reply = getattr(be.shards, "_stat_rpc", None)
                if reply is not None:
                    r = be.shards._stat_rpc(s, self.oid, True)
                    if r is not None and r.result == 0:
                        return r.attrs.get(name)
                    continue
                # local backend: direct store access
                from ..osd.ec_transaction import shard_oid
                try:
                    return be.shards.store.getattr(
                        be.shards.cids[s], shard_oid(self.oid, s), name)
                except KeyError:
                    return None
        else:
            from ..osd.types import NO_SHARD, ghobject_t, spg_t
            try:
                return self.daemon.store.getattr(
                    self.daemon._cid(spg_t(self.pgid, NO_SHARD)),
                    ghobject_t(self.oid, shard=NO_SHARD), name)
            except KeyError:
                return None
        return None

    # -- staged mutations (committed as one PGTransaction) ------------------

    def setxattr(self, name: str, value: bytes) -> None:
        self._pending_attrs[name] = bytes(value)

    def rmxattr(self, name: str) -> None:
        self._pending_attrs[name] = None

    def write_full(self, data: bytes) -> None:
        self._pending_write = (0, bytes(data))

    def has_mutations(self) -> bool:
        return bool(self._pending_attrs) or self._pending_write is not None


# -- built-in classes --------------------------------------------------------

from . import (cls_journal, cls_lock, cls_log,  # noqa: E402,F401
               cls_numops, cls_refcount, cls_rgw, cls_user)
