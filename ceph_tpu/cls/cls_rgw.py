"""cls_rgw-role: the bucket directory object class.

Re-expresses the slice of reference src/cls/rgw/cls_rgw.cc the gateway
needs: the bucket index lives in a directory object the OSD mutates
server-side, so index updates are atomic with respect to each other
(reference cls_rgw_bucket_dir_entry + rgw_bucket_dir ops; the OSD
serializes CALL ops per object).

Idiomatic shift: the reference keeps one omap row per entry; here the
directory is a JSON document in the object body (this build's EC/
replicated PGTransaction does not carry omap — and the reference also
restricts omap to replicated pools, so index pools are small-metadata
pools either way).  The op surface (add/rm/list with prefix+marker
pagination) is the same.

Reserved doc keys: "@next" (log_append's sequence row) and
"@tombstones" (reshard dual-write deletion intents, see dir_rm /
dir_merge).  "@tombstones" is excluded from dir_list/dir_count; the
planes that shard (index/versions) never store user rows named
"@tombstones" (S3 keys can technically start with "@", but the exact
string "@tombstones" colliding is a documented deviation, accepted
for the same reason reference cls_rgw reserves its BI_PREFIX_CHAR
namespace).
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict

from . import ClsError, register_class

# Parsed-doc cache: directory docs are read-modify-written whole, so
# without it every dir op re-parses the full JSON doc — O(doc) per
# call, which makes a LIST PAGE cost grow with bucket size instead of
# page size.  Keyed per (daemon, oid) and guarded by a digest of the
# raw bytes: any out-of-band change to the object (recovery adoption,
# another primary after an interval change, a failed commit) just
# misses and re-parses, so the cache can never serve a stale doc.
# Entries hand out COPIES (top level + the "@tombstones" row, the
# only nested dict methods mutate in place): per-object call
# serialization protects the doc a method mutates, but a cached dict
# shared across calls would not survive concurrent dir_list readers.
# Per-entry meta dicts are shared — every method replaces them whole,
# never edits them.
_DOC_CACHE_MAX = 64
_doc_cache: OrderedDict = OrderedDict()
_doc_mu = threading.Lock()


def _cache_key(ctx) -> tuple:
    return (id(ctx.daemon), getattr(ctx.oid, "name", str(ctx.oid)))


def _copy_doc(d: dict) -> dict:
    c = dict(d)
    ts = c.get("@tombstones")
    if ts is not None:
        c["@tombstones"] = dict(ts)
    return c


def _cache_put(key: tuple, dig: bytes, d: dict) -> None:
    with _doc_mu:
        _doc_cache[key] = (dig, _copy_doc(d))
        _doc_cache.move_to_end(key)
        while len(_doc_cache) > _DOC_CACHE_MAX:
            _doc_cache.popitem(last=False)


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {}
    key = _cache_key(ctx)
    dig = hashlib.md5(raw).digest()
    with _doc_mu:
        hit = _doc_cache.get(key)
        if hit is not None and hit[0] == dig:
            _doc_cache.move_to_end(key)
            return _copy_doc(hit[1])
    try:
        d = json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt bucket dir: {e}") from e
    _cache_put(key, dig, d)
    return d


def _store(ctx, d: dict) -> None:
    raw = json.dumps(d, separators=(",", ":")).encode()
    ctx.write_full(raw)
    # cache the post-write doc under the bytes being committed; if
    # the transaction never lands, the next read's digest misses
    _cache_put(_cache_key(ctx), hashlib.md5(raw).digest(), d)


def dir_init(ctx, _inp: bytes) -> bytes:
    if not ctx.read():
        _store(ctx, {})
    return b""


def dir_add(ctx, inp: bytes) -> bytes:
    """input: {"key": str, "meta": {...}} — upsert one entry.  A
    re-add supersedes any reshard tombstone for the key (the put
    happened after the delete in this shard's serial order)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    d[req["key"]] = req.get("meta", {})
    ts = d.get("@tombstones")
    if ts and ts.pop(req["key"], None) is not None and not ts:
        del d["@tombstones"]
    _store(ctx, d)
    return b""


def dir_rm(ctx, inp: bytes) -> bytes:
    """input: {"key": str, "tombstone": bool?}.  Plain rm errors on a
    missing key (ENOENT).  tombstone mode is the reshard dual-write
    delete: it never errors and records the deletion intent under
    "@tombstones" so a later dir_merge if_absent copy of a stale entry
    from the old shard set cannot resurrect the key."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    if req.get("tombstone"):
        d.pop(req["key"], None)
        d.setdefault("@tombstones", {})[req["key"]] = 1
        _store(ctx, d)
        return b""
    if req["key"] not in d:
        raise ClsError(2, "no such key")
    del d[req["key"]]
    _store(ctx, d)
    return b""


def dir_merge(ctx, inp: bytes) -> bytes:
    """input: {"entries": [[key, meta]...], "if_absent": bool} — batch
    upsert, one atomic class call per page (the resharder's copy op).
    if_absent skips keys already present OR tombstoned: a dual-write
    that landed on the new shard first (newer data, or a delete) must
    win over the copier's snapshot of the old shard.  -> number of
    entries applied."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    if_absent = bool(req.get("if_absent"))
    ts = d.get("@tombstones", {})
    applied = 0
    for k, meta in req.get("entries", []):
        if if_absent and (k in d or k in ts):
            continue
        d[k] = meta
        applied += 1
    if applied:
        _store(ctx, d)
    return str(applied).encode()


def dir_reshard_clean(ctx, _inp: bytes) -> bytes:
    """Drop the "@tombstones" row after reshard cutover (old shards
    reaped; nothing left to merge against)."""
    d = _load(ctx)
    if d.pop("@tombstones", None) is not None:
        _store(ctx, d)
    return b""


def dir_get(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    ent = d.get(req["key"])
    if ent is None:
        raise ClsError(2, "no such key")
    return json.dumps(ent).encode()


def dir_list(ctx, inp: bytes) -> bytes:
    """input: {"prefix": str, "marker": str, "from": str, "max": int}
    -> {"entries": [[key, meta]...], "truncated": bool} in key order
    (reference rgw_bucket_dir list with pagination).  "marker" is an
    EXCLUSIVE lower bound (keys > marker); "from" is INCLUSIVE (keys
    >= from) — delimiter pagination resumes at a computed successor
    that must not itself be skippable."""
    req = json.loads(inp.decode()) if inp else {}
    prefix = req.get("prefix", "")
    marker = req.get("marker", "")
    resume = req.get("from", "")
    limit = int(req.get("max", 1000))
    d = _load(ctx)
    keys = sorted(k for k in d
                  if k != "@tombstones"
                  and k.startswith(prefix) and k > marker
                  and (not resume or k >= resume))
    out = [[k, d[k]] for k in keys[:limit]]
    return json.dumps({"entries": out,
                       "truncated": len(keys) > limit}).encode()


def dir_count(ctx, _inp: bytes) -> bytes:
    d = _load(ctx)
    return str(len(d) - ("@tombstones" in d)).encode()


def log_append(ctx, inp: bytes) -> bytes:
    """Append with server-side sequence allocation: the "@next" meta
    row is read+bumped in the same atomic class call, so concurrent
    writers can never collide on a sequence number (journal role;
    reference journal object append exclusivity)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    seq = int(d.get("@next", {}).get("seq", 0))
    d[f"{seq:016x}"] = req.get("meta", {})
    d["@next"] = {"seq": seq + 1}
    _store(ctx, d)
    return str(seq).encode()


register_class("rgw", {
    "dir_init": dir_init,
    "dir_add": dir_add,
    "dir_rm": dir_rm,
    "dir_merge": dir_merge,
    "dir_reshard_clean": dir_reshard_clean,
    "dir_get": dir_get,
    "dir_list": dir_list,
    "dir_count": dir_count,
    "log_append": log_append,
})
