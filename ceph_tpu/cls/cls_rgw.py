"""cls_rgw-role: the bucket directory object class.

Re-expresses the slice of reference src/cls/rgw/cls_rgw.cc the gateway
needs: the bucket index lives in a directory object the OSD mutates
server-side, so index updates are atomic with respect to each other
(reference cls_rgw_bucket_dir_entry + rgw_bucket_dir ops; the OSD
serializes CALL ops per object).

Idiomatic shift: the reference keeps one omap row per entry; here the
directory is a JSON document in the object body (this build's EC/
replicated PGTransaction does not carry omap — and the reference also
restricts omap to replicated pools, so index pools are small-metadata
pools either way).  The op surface (add/rm/list with prefix+marker
pagination) is the same.
"""

from __future__ import annotations

import json

from . import ClsError, register_class


def _load(ctx) -> dict:
    raw = ctx.read()
    if not raw:
        return {}
    try:
        return json.loads(raw.decode())
    except ValueError as e:
        raise ClsError(5, f"corrupt bucket dir: {e}") from e


def _store(ctx, d: dict) -> None:
    ctx.write_full(json.dumps(d, separators=(",", ":")).encode())


def dir_init(ctx, _inp: bytes) -> bytes:
    if not ctx.read():
        _store(ctx, {})
    return b""


def dir_add(ctx, inp: bytes) -> bytes:
    """input: {"key": str, "meta": {...}} — upsert one entry."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    d[req["key"]] = req.get("meta", {})
    _store(ctx, d)
    return b""


def dir_rm(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    if req["key"] not in d:
        raise ClsError(2, "no such key")
    del d[req["key"]]
    _store(ctx, d)
    return b""


def dir_get(ctx, inp: bytes) -> bytes:
    req = json.loads(inp.decode())
    d = _load(ctx)
    ent = d.get(req["key"])
    if ent is None:
        raise ClsError(2, "no such key")
    return json.dumps(ent).encode()


def dir_list(ctx, inp: bytes) -> bytes:
    """input: {"prefix": str, "marker": str, "from": str, "max": int}
    -> {"entries": [[key, meta]...], "truncated": bool} in key order
    (reference rgw_bucket_dir list with pagination).  "marker" is an
    EXCLUSIVE lower bound (keys > marker); "from" is INCLUSIVE (keys
    >= from) — delimiter pagination resumes at a computed successor
    that must not itself be skippable."""
    req = json.loads(inp.decode()) if inp else {}
    prefix = req.get("prefix", "")
    marker = req.get("marker", "")
    resume = req.get("from", "")
    limit = int(req.get("max", 1000))
    d = _load(ctx)
    keys = sorted(k for k in d
                  if k.startswith(prefix) and k > marker
                  and (not resume or k >= resume))
    out = [[k, d[k]] for k in keys[:limit]]
    return json.dumps({"entries": out,
                       "truncated": len(keys) > limit}).encode()


def dir_count(ctx, _inp: bytes) -> bytes:
    return str(len(_load(ctx))).encode()


def log_append(ctx, inp: bytes) -> bytes:
    """Append with server-side sequence allocation: the "@next" meta
    row is read+bumped in the same atomic class call, so concurrent
    writers can never collide on a sequence number (journal role;
    reference journal object append exclusivity)."""
    req = json.loads(inp.decode())
    d = _load(ctx)
    seq = int(d.get("@next", {}).get("seq", 0))
    d[f"{seq:016x}"] = req.get("meta", {})
    d["@next"] = {"seq": seq + 1}
    _store(ctx, d)
    return str(seq).encode()


register_class("rgw", {
    "dir_init": dir_init,
    "dir_add": dir_add,
    "dir_rm": dir_rm,
    "dir_get": dir_get,
    "dir_list": dir_list,
    "dir_count": dir_count,
    "log_append": log_append,
})
