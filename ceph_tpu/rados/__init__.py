"""librados-like public client API (reference src/librados/)."""

from .client import IoCtx, RadosClient

__all__ = ["RadosClient", "IoCtx"]
