"""Client-side striping over RADOS objects.

Re-expresses reference src/libradosstriper/ (RadosStriperImpl): a large
logical object is striped over many RADOS objects with a
(stripe_unit, stripe_count, object_size) policy — the storage analog of
sequence sharding (SURVEY.md section 5 "long-context").  Layout matches
the reference's: stripe units round-robin across a set of
`stripe_count` objects until each reaches `object_size`, then the next
object set begins.  The logical size rides an xattr on the first
object.
"""

from __future__ import annotations

import errno

from .client import IoCtx, RadosError

SIZE_XATTR = "striper.size"
LAYOUT_XATTR = "striper.layout"


class StripedObject:
    def __init__(self, ioctx: IoCtx, name: str,
                 stripe_unit: int = 4096, stripe_count: int = 4,
                 object_size: int = 1 << 22):
        assert object_size % stripe_unit == 0
        self.io = ioctx
        self.name = name
        self.su = stripe_unit
        self.sc = stripe_count
        self.os_ = object_size

    def _piece(self, idx: int) -> str:
        return f"{self.name}.{idx:016x}"

    def _map(self, off: int) -> tuple[int, int, int]:
        """logical offset -> (object index, object offset, run length
        to the end of this stripe unit)."""
        set_size = self.os_ * self.sc          # bytes per object set
        set_idx, set_off = divmod(off, set_size)
        stripe, stripe_off = divmod(set_off, self.su * self.sc)
        within, unit_off = divmod(stripe_off, self.su)
        obj_idx = set_idx * self.sc + within
        obj_off = stripe * self.su + unit_off
        run = self.su - unit_off
        return obj_idx, obj_off, run

    # -- I/O ----------------------------------------------------------------

    def write(self, data: bytes, offset: int = 0) -> None:
        pos = 0
        while pos < len(data):
            obj_idx, obj_off, run = self._map(offset + pos)
            chunk = data[pos:pos + run]
            self.io.write(self._piece(obj_idx), chunk, offset=obj_off)
            pos += len(chunk)
        new_size = offset + len(data)
        if new_size > self.size():
            self._set_size(new_size)

    def read(self, length: int | None = None, offset: int = 0) -> bytes:
        size = self.size()
        if length is None:
            length = size - offset
        length = max(0, min(length, size - offset))
        out = bytearray()
        pos = 0
        while pos < length:
            obj_idx, obj_off, run = self._map(offset + pos)
            want = min(run, length - pos)
            try:
                piece = self.io.read(self._piece(obj_idx), want, obj_off)
            except RadosError as e:
                if e.errno == errno.ENOENT:
                    piece = b"\0" * want     # sparse hole
                else:
                    raise
            if len(piece) < want:
                piece = piece + b"\0" * (want - len(piece))
            out += piece
            pos += want
        return bytes(out)

    def size(self) -> int:
        """Logical size from the striper metadata object (the reference
        keeps it in an xattr of piece 0; our IoCtx surface keeps object
        data as the metadata channel)."""
        try:
            raw = self.io.read(self._size_obj(), 0)
            return int(raw.decode() or "0")
        except RadosError:
            return 0

    def _size_obj(self) -> str:
        return f"{self.name}.striper_meta"

    def _set_size(self, size: int) -> None:
        self.io.write_full(self._size_obj(), str(size).encode())

    def remove(self) -> None:
        size = self.size()
        set_size = self.os_ * self.sc
        nsets = -(-max(size, 1) // set_size)
        for idx in range(nsets * self.sc):
            try:
                self.io.remove(self._piece(idx))
            except RadosError:
                pass
        try:
            self.io.remove(self._size_obj())
        except RadosError:
            pass
