"""RadosClient / IoCtx: the public client API.

Re-expresses the reference librados surface (src/librados/librados.cc,
RadosClient/IoCtxImpl; python binding src/pybind/rados/rados.pyx):
connect to the cluster, open an IoCtx per pool, then object I/O —
write_full / write / append / read / stat / remove / truncate /
setxattr — plus pool and EC-profile administration via mon commands.
Synchronous surface over the async Objecter (aio_* variants return
concurrent futures).
"""

from __future__ import annotations

import errno
from concurrent.futures import ThreadPoolExecutor, Future

from ..osdc import Objecter


class RadosError(Exception):
    def __init__(self, err: int, msg: str = ""):
        super().__init__(f"[errno {err}] {msg}")
        self.errno = err


class RadosClient:
    def __init__(self, mon_addr, name: str = "client", auth=None,
                 secure: bool = False, compress: str | None = None):
        self.objecter = Objecter(mon_addr, name, auth=auth,
                                 secure=secure, compress=compress)
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="rados-aio")

    def connect(self) -> "RadosClient":
        self.objecter.start()
        return self

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
        self.objecter.shutdown()

    # -- pool admin ---------------------------------------------------------

    def mon_command(self, cmd: dict) -> tuple[int, dict]:
        return self.objecter.mon_command(cmd)

    def create_pool(self, name: str, pool_type: str = "replicated",
                    **kw) -> dict:
        cmd = {"prefix": "osd pool create", "name": name,
               "type": pool_type, **kw}
        result, out = self.mon_command(cmd)
        if result != 0:
            raise RadosError(-result, out.get("error", "pool create failed"))
        return out

    def set_ec_profile(self, name: str, profile: dict) -> dict:
        result, out = self.mon_command(
            {"prefix": "osd erasure-code-profile set", "name": name,
             "profile": profile})
        if result != 0:
            raise RadosError(-result, out.get("error", "profile set failed"))
        return out

    def pool_list(self) -> list[str]:
        result, out = self.mon_command({"prefix": "osd pool ls"})
        return out.get("pools", [])

    def status(self) -> dict:
        result, out = self.mon_command({"prefix": "status"})
        return out

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        self.objecter.refresh_map()
        pool = self.objecter.osdmap.lookup_pool(pool_name)
        if pool is None:
            raise RadosError(errno.ENOENT, f"no pool {pool_name}")
        return IoCtx(self, pool.id, pool_name)


class IoCtx:
    def __init__(self, client: RadosClient, pool_id: int, pool_name: str):
        self.client = client
        self.pool_id = pool_id
        self.pool_name = pool_name
        # self-managed snapshots (reference rados_ioctx_selfmanaged_*):
        # snapc rides every write; read_snap redirects reads to a clone
        self.snapc: list | None = None     # [seq, [snap ids desc]]
        self.read_snap: int = 0
        # QoS class every op of this ioctx declares on the wire (the
        # mClock scheduler's per-tenant key; None = plain "client")
        self.qos_class: str | None = None

    def set_qos_class(self, qos_class: str | None) -> None:
        """Tag this ioctx's ops with an mClock QoS class (tenant name);
        the OSD schedules them under that class's (reservation,
        weight, limit) triple — see docs/QOS.md."""
        self.qos_class = qos_class

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        self.snapc = [int(seq), [int(s) for s in snaps]]

    def set_read_snap(self, snap: int) -> None:
        self.read_snap = int(snap)

    def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id from the mon (reference
        rados_ioctx_selfmanaged_snap_create)."""
        r, out = self.client.mon_command({
            "prefix": "osd pool selfmanaged-snap-create",
            "pool": self.pool_name})
        if r != 0:
            raise RadosError(-r, out.get("error", "snap create"))
        return int(out["snapid"])

    def selfmanaged_snap_remove(self, snapid: int) -> None:
        """Mark a snap id deleted; its clones are reclaimed by the
        OSD snap trimmer (reference rados_ioctx_selfmanaged_snap_remove
        + the snap trim queue)."""
        r, out = self.client.mon_command({
            "prefix": "osd pool selfmanaged-snap-rm",
            "pool": self.pool_name, "snapid": snapid})
        if r != 0:
            raise RadosError(-r, out.get("error", "snap rm"))

    def _submit(self, name: str, ops: list, data: bytes = b"",
                snap: int = 0) -> bytes:
        reply = self.client.objecter.op_submit(
            self.pool_id, name, ops, data, snap=snap,
            snapc=self.snapc, qos_class=self.qos_class)
        if reply.result != 0:
            raise RadosError(-reply.result, f"op on {name}")
        return reply.data

    # -- sync I/O -----------------------------------------------------------

    def write_full(self, name: str, data: bytes) -> None:
        self._submit(name, [["writefull", len(data)]], bytes(data))

    def write(self, name: str, data: bytes, offset: int = 0) -> None:
        self._submit(name, [["write", offset, len(data)]], bytes(data))

    def read(self, name: str, length: int = 0, offset: int = 0,
             snap: int | None = None) -> bytes:
        return self._submit(name, [["read", offset, length]],
                            snap=self.read_snap if snap is None
                            else snap)

    def stat(self, name: str) -> int:
        self._submit(name, [["stat"]])
        return 0  # size via read for now; meta channel reserved

    def remove(self, name: str) -> None:
        self._submit(name, [["delete"]])

    def truncate(self, name: str, size: int) -> None:
        self._submit(name, [["truncate", size]])

    def setxattr(self, name: str, key: str, value: bytes) -> None:
        self._submit(name, [["setxattr", key, len(value)]], bytes(value))

    def getxattr(self, name: str, key: str) -> bytes:
        return bytes(self._submit(name, [["getxattr", key]]))

    def rmxattr(self, name: str, key: str) -> None:
        self._submit(name, [["rmxattr", key]])

    def cmpxattr(self, name: str, key: str, value: bytes) -> None:
        """Guard: raises RadosError(ECANCELED) unless the xattr
        currently equals `value` (reference rados_cmpxattr EQ)."""
        self._submit(name, [["cmpxattr", key, len(value)]],
                     bytes(value))

    def append(self, name: str, data: bytes) -> None:
        """reference rados_append: write at the current size."""
        self._submit(name, [["append", len(data)]], bytes(data))

    def zero(self, name: str, off: int, length: int) -> None:
        """reference rados_zero: logical zeros over a range."""
        self._submit(name, [["zero", off, length]])

    def create(self, name: str, exclusive: bool = True) -> None:
        """reference rados_create: make an empty object; exclusive
        raises EEXIST if it already exists."""
        self._submit(name, [["create", 1 if exclusive else 0]])

    # -- omap (reference rados_omap_* / ObjectWriteOperation omap ops;
    #    OSD-side: the OMAP cases of PrimaryLogPG::do_osd_ops) ---------------

    def omap_set(self, name: str, kv: dict[bytes, bytes]) -> None:
        from ..common import omap_codec as oc
        payload = oc.encode_kv(kv)
        self._submit(name, [["omapsetkeys", len(payload)]], payload)

    def omap_rm_keys(self, name: str, keys) -> None:
        from ..common import omap_codec as oc
        payload = oc.encode_keys(keys)
        self._submit(name, [["omaprmkeys", len(payload)]], payload)

    def omap_clear(self, name: str) -> None:
        self._submit(name, [["omapclear"]])

    def omap_set_header(self, name: str, data: bytes) -> None:
        self._submit(name, [["omapsetheader", len(data)]], bytes(data))

    def omap_get_header(self, name: str) -> bytes:
        return self._submit(name, [["omapgetheader"]])

    def omap_get_keys(self, name: str, start_after: bytes | None = None,
                      max_return: int = 0) -> list[bytes]:
        from ..common import omap_codec as oc
        sa = oc.encode_keys([start_after] if start_after else [])
        out = self._submit(
            name, [["omapgetkeys", len(sa), max_return]], sa)
        keys, _ = oc.decode_keys(out)
        return keys

    def omap_get_vals(self, name: str, start_after: bytes | None = None,
                      max_return: int = 0) -> dict[bytes, bytes]:
        from ..common import omap_codec as oc
        sa = oc.encode_keys([start_after] if start_after else [])
        out = self._submit(
            name, [["omapgetvals", len(sa), max_return]], sa)
        kv, _ = oc.decode_kv(out)
        return kv

    def omap_get_vals_by_keys(self, name: str,
                              keys) -> dict[bytes, bytes]:
        from ..common import omap_codec as oc
        payload = oc.encode_keys(keys)
        out = self._submit(
            name, [["omapgetvalsbykeys", len(payload)]], payload)
        kv, _ = oc.decode_kv(out)
        return kv

    # -- cls / watch-notify --------------------------------------------------

    def execute(self, name: str, cls: str, method: str,
                inp: bytes = b"") -> bytes:
        """Server-side class call (reference rados_exec / IoCtx::exec)."""
        return self._submit(name, [["call", f"{cls}.{method}", len(inp)]],
                            bytes(inp))

    def watch(self, name: str, callback) -> int:
        """callback(oid_name, payload) fires on each notify."""
        return self.client.objecter.watch(self.pool_id, name, callback)

    def list_watchers(self, name: str) -> list[int]:
        """Cookies of live watchers (reference rados_watchers_list)."""
        import json
        return json.loads(self._submit(name, [["listwatchers"]]).decode())

    def unwatch(self, name: str, cookie: int) -> None:
        self.client.objecter.unwatch(self.pool_id, name, cookie)

    def notify(self, name: str, payload: bytes = b"") -> None:
        self.client.objecter.notify(self.pool_id, name, payload)

    # -- async --------------------------------------------------------------

    def aio_write_full(self, name: str, data: bytes) -> Future:
        return self.client._pool.submit(self.write_full, name, data)

    def aio_read(self, name: str, length: int = 0, offset: int = 0) -> Future:
        return self.client._pool.submit(self.read, name, length, offset)
