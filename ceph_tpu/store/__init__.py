"""Local object stores (reference src/os/)."""

from .object_store import ObjectStore, Transaction
from .mem_store import MemStore

__all__ = ["ObjectStore", "Transaction", "MemStore"]
