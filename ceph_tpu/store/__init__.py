"""Local object stores (reference src/os/)."""

from .object_store import ObjectStore, Transaction
from .mem_store import MemStore


def create_store(kind: str, path: str | None = None) -> ObjectStore:
    """reference ObjectStore::create (src/ceph_osd.cc:286): pick a
    backend by name."""
    if kind == "memstore":
        return MemStore()
    if kind == "filestore":
        from .file_store import FileStore
        assert path, "filestore needs a path"
        return FileStore(path)
    if kind.startswith("bluestore"):
        from .blue_store import BlueStore
        assert path, "bluestore needs a path"
        # "bluestore" or "bluestore-<compressor>" (zlib/bz2/lzma)
        return BlueStore(path,
                         compression=kind.partition("-")[2] or None)
    raise ValueError(f"unknown objectstore {kind!r}")


__all__ = ["ObjectStore", "Transaction", "MemStore", "create_store"]
