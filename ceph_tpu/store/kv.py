"""KeyValueDB abstraction + implementations.

Re-expresses reference src/kv/ (KeyValueDB.h + RocksDBStore/MemDB): a
prefixed key-value store with atomic write batches, backing store
metadata (and, in the reference, the entire mon store).  Implementations:

  MemDB — dict-backed (reference MemDB role; tests)
  LogDB — durable WAL + whole-file snapshot (kept for small stores and
          as the round-4 comparison point; O(total-keys) compaction)
  LsmDB — the real engine (kv_lsm.py): memtable + WAL + block-based
          SSTables + leveled compaction, the RocksDBStore role.  All
          durable subsystems (BlueStore-role metadata, FileStore omap,
          the mon store) ride this one.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from pathlib import Path

from ..common import crc32c as _crc


class WriteBatch:
    def __init__(self):
        self.ops: list[tuple] = []   # ("set", k, v) | ("rm", k)

    def set(self, key: bytes, value: bytes) -> None:
        self.ops.append(("set", bytes(key), bytes(value)))

    def rm(self, key: bytes) -> None:
        self.ops.append(("rm", bytes(key)))


class KeyValueDB:
    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def submit(self, batch: WriteBatch, sync: bool = True) -> None:
        raise NotImplementedError

    def iterate(self, prefix: bytes = b""):
        raise NotImplementedError

    def set(self, key: bytes, value: bytes) -> None:
        b = WriteBatch()
        b.set(key, value)
        self.submit(b)

    def rm(self, key: bytes) -> None:
        b = WriteBatch()
        b.rm(key)
        self.submit(b)

    def close(self) -> None:
        pass


class MemDB(KeyValueDB):
    def __init__(self):
        self._d: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def get(self, key):
        with self._lock:
            return self._d.get(bytes(key))

    def submit(self, batch, sync=True):
        with self._lock:
            for op in batch.ops:
                if op[0] == "set":
                    self._d[op[1]] = op[2]
                else:
                    self._d.pop(op[1], None)

    def iterate(self, prefix=b""):
        with self._lock:
            items = sorted((k, v) for k, v in self._d.items()
                           if k.startswith(prefix))
        yield from items


class LogDB(KeyValueDB):
    """WAL + snapshot durable KV."""

    MAGIC = b"KVL1"

    def __init__(self, path: str, compact_every: int = 4096):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.snap = self.dir / "snapshot.json"
        self.wal = self.dir / "wal.log"
        self._d: dict[bytes, bytes] = {}
        self._lock = threading.RLock()
        self._since_compact = 0
        self.compact_every = compact_every
        self._replay()
        self._wal_f = open(self.wal, "ab")

    # -- recovery -----------------------------------------------------------

    def _replay(self) -> None:
        if self.snap.exists():
            raw = json.loads(self.snap.read_text())
            self._d = {bytes.fromhex(k): bytes.fromhex(v)
                       for k, v in raw.items()}
        if self.wal.exists():
            good = 0
            with open(self.wal, "rb") as f:
                while True:
                    head = f.read(8)
                    if len(head) < 8:
                        break
                    ln, crc = struct.unpack("<II", head)
                    body = f.read(ln)
                    if len(body) < ln or \
                            _crc.crc32c(body, 0xFFFFFFFF) != crc:
                        break  # torn tail: stop replay (reference WAL)
                    good = f.tell()
                    for op in json.loads(body.decode()):
                        if op[0] == "set":
                            self._d[bytes.fromhex(op[1])] = \
                                bytes.fromhex(op[2])
                        else:
                            self._d.pop(bytes.fromhex(op[1]), None)
            if good < self.wal.stat().st_size:
                # truncate the torn bytes so post-restart appends are
                # not stranded behind a permanently unreadable record
                with open(self.wal, "r+b") as f:
                    f.truncate(good)
                    f.flush()
                    os.fsync(f.fileno())

    # -- API ----------------------------------------------------------------

    def get(self, key):
        with self._lock:
            return self._d.get(bytes(key))

    def submit(self, batch, sync=True):
        recs = []
        for op in batch.ops:
            if op[0] == "set":
                recs.append(["set", op[1].hex(), op[2].hex()])
            else:
                recs.append(["rm", op[1].hex()])
        body = json.dumps(recs).encode()
        head = struct.pack("<II", len(body),
                           _crc.crc32c(body, 0xFFFFFFFF))
        with self._lock:
            self._wal_f.write(head + body)
            self._wal_f.flush()
            if sync:
                os.fsync(self._wal_f.fileno())
            for op in batch.ops:
                if op[0] == "set":
                    self._d[op[1]] = op[2]
                else:
                    self._d.pop(op[1], None)
            self._since_compact += 1
            if self._since_compact >= self.compact_every:
                self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self.snap.with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {k.hex(): v.hex() for k, v in self._d.items()}))
        os.replace(tmp, self.snap)
        self._wal_f.close()
        self._wal_f = open(self.wal, "wb")
        self._since_compact = 0

    def compact(self) -> None:
        with self._lock:
            self._compact_locked()

    def iterate(self, prefix=b""):
        with self._lock:
            items = sorted((k, v) for k, v in self._d.items()
                           if k.startswith(prefix))
        yield from items

    def close(self) -> None:
        with self._lock:
            self._wal_f.close()


def open_kv(path: str | None, **kw) -> KeyValueDB:
    """Factory: the durable default is the LSM engine; no path = MemDB.
    (Reference analog: KeyValueDB::create picking RocksDBStore,
    src/kv/KeyValueDB.cc.)  A data dir written by the old LogDB format
    (snapshot.json / wal.log) is migrated in place on first open."""
    if not path:
        return MemDB()
    from .kv_lsm import LsmDB
    p = Path(path)
    old_snap, old_wal = p / "snapshot.json", p / "wal.log"
    if old_snap.exists() or old_wal.exists():
        old = LogDB(path)
        items = list(old.iterate())
        old.close()
        db = LsmDB(path, **kw)
        batch = WriteBatch()
        for k, v in items:
            batch.set(k, v)
        if batch.ops:
            db.submit(batch)
        db.compact()                 # settle into SSTs before the old
        old_snap.unlink(missing_ok=True)   # artifacts disappear
        old_wal.unlink(missing_ok=True)
        return db
    return LsmDB(path, **kw)
