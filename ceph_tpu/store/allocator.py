"""Block allocator for the raw-block store.

Re-expresses reference src/os/bluestore/Allocator.h (+ the btree/bitmap
allocator family) at the policy this store needs: first-fit over an
offset-sorted free-extent map with merge-on-release, min_alloc_size
granularity, and grow-on-demand (the "device" is a plain file, so
running past the end extends it instead of ENOSPC).

The free map is NOT persisted: mount rebuilds it by walking every
onode's blob extents (the role of BlueStore's fsck-style realloc;
the reference persists a FreelistManager in the KV — rebuilding from
authoritative metadata is the simpler crash-safe equivalent at this
scale, and makes allocator state impossible to desync from the onodes).
"""

from __future__ import annotations

import threading


class Allocator:
    def __init__(self, size: int, min_alloc: int = 4096):
        self.min_alloc = min_alloc
        self.size = size
        self._lock = threading.Lock()
        # offset -> length of free extents, kept merged + sorted
        self._free: dict[int, int] = {0: size} if size else {}

    # -- carving -------------------------------------------------------------

    def allocate(self, want: int) -> list[tuple[int, int]]:
        """First-fit extents totalling `want` (rounded up to
        min_alloc); grows the device when free space runs out."""
        want = -(-want // self.min_alloc) * self.min_alloc
        out: list[tuple[int, int]] = []
        with self._lock:
            remaining = want
            for off in sorted(self._free):
                if remaining <= 0:
                    break
                length = self._free.pop(off)
                take = min(length, remaining)
                out.append((off, take))
                if take < length:
                    self._free[off + take] = length - take
                remaining -= take
            if remaining > 0:
                # grow the device file
                out.append((self.size, remaining))
                self.size += remaining
        return out

    def release(self, extents) -> None:
        with self._lock:
            for off, length in extents:
                self._free[off] = length
            self._merge()

    def mark_used(self, off: int, length: int) -> None:
        """Carve a specific range out of the free map (mount-time
        rebuild from onode metadata)."""
        with self._lock:
            if off + length > self.size:
                self.size = off + length
            for foff in sorted(self._free):
                flen = self._free[foff]
                fend = foff + flen
                if fend <= off or foff >= off + length:
                    continue
                del self._free[foff]
                if foff < off:
                    self._free[foff] = off - foff
                if fend > off + length:
                    self._free[off + length] = fend - (off + length)

    def _merge(self) -> None:
        merged: dict[int, int] = {}
        last_off = last_len = None
        for off in sorted(self._free):
            length = self._free[off]
            if last_off is not None and last_off + last_len == off:
                last_len += length
            else:
                if last_off is not None:
                    merged[last_off] = last_len
                last_off, last_len = off, length
        if last_off is not None:
            merged[last_off] = last_len
        self._free = merged

    def free_bytes(self) -> int:
        with self._lock:
            return sum(self._free.values())
