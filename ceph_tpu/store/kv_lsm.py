"""LsmDB — a leveled log-structured merge KeyValueDB.

Re-expresses the role of the reference's RocksDBStore (src/kv/
RocksDBStore.{h,cc}: the KV engine beneath BlueStore metadata, the mon
store, and PG-meta omap).  The previous LogDB rewrote a whole-DB JSON
snapshot every N commits — O(total keys) compaction, a scaling floor.
LsmDB has the real machinery, sized down to this build:

  memtable   dict + tombstones, byte-budgeted
  WAL        crc-framed append log (same torn-tail recovery discipline
             as LogDB / the reference WAL), one file per memtable
  SSTables   immutable sorted runs of 4 KiB crc'd blocks with a sparse
             (first-key-per-block) index in the footer — point reads
             touch one block, memory holds only the index
  manifest   the current version (files per level, next seq), replaced
             atomically; recovery = manifest + WAL replay
  compaction leveled: L0 accumulates whole memtables (overlapping);
             L0 full -> merge with overlapping L1 files; level over
             budget -> merge one file down.  I/O per compaction is
             bounded by the sizes of the participating files, never
             the whole DB.

Deliberate deviations from RocksDB: no bloom filters (point-miss cost
is one block read per touched level), no column families (the prefix
convention covers the callers), single-writer (callers serialize via
the store's op pipeline; the GIL would anyway).
"""

from __future__ import annotations

import bisect
import heapq
import json
import os
import struct
import threading
from pathlib import Path

from ..common import crc32c as _crc
from .kv import KeyValueDB, WriteBatch

_TOMBSTONE = 0xFFFFFFFF          # vlen sentinel for deletes inside SSTs
_SST_MAGIC = b"SST1"
_WAL_MAGIC = b"KVW1"


def _fsync_dir(path: Path) -> None:
    fd = os.open(str(path), os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# ----------------------------------------------------------------------------
# SSTable
# ----------------------------------------------------------------------------

class SSTWriter:
    """Streams sorted (key, value|None) records into crc'd blocks."""

    def __init__(self, path: Path, block_size: int = 4096):
        self.path = path
        self.block_size = block_size
        self.f = open(path, "wb")
        self.f.write(_SST_MAGIC)
        self.index: list[tuple[bytes, int]] = []  # (first_key, offset)
        self._block = bytearray()
        self._block_first: bytes | None = None
        self.count = 0
        self.min_key: bytes | None = None
        self.max_key: bytes | None = None

    def add(self, key: bytes, value: bytes | None) -> None:
        if self._block_first is None:
            self._block_first = key
        vlen = _TOMBSTONE if value is None else len(value)
        self._block += struct.pack("<HI", len(key), vlen) + key
        if value is not None:
            self._block += value
        self.count += 1
        if self.min_key is None:
            self.min_key = key
        self.max_key = key
        if len(self._block) >= self.block_size:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._block:
            return
        payload = bytes(self._block)
        self.index.append((self._block_first, self.f.tell()))
        self.f.write(struct.pack(
            "<II", len(payload), _crc.crc32c(payload, 0xFFFFFFFF)))
        self.f.write(payload)
        self._block = bytearray()
        self._block_first = None

    def finish(self) -> None:
        self._flush_block()
        idx_off = self.f.tell()
        idx = bytearray()
        for first, off in self.index:
            idx += struct.pack("<HQ", len(first), off) + first
        payload = bytes(idx)
        self.f.write(payload)
        self.f.write(struct.pack(
            "<QII", idx_off, len(payload),
            _crc.crc32c(payload, 0xFFFFFFFF)))
        self.f.write(_SST_MAGIC)
        self.f.flush()
        os.fsync(self.f.fileno())
        self.f.close()


class SSTReader:
    """Sparse-index reader; keeps the fd open so compaction can unlink
    the file under live iterators (POSIX keeps the inode alive).

    `pins` counts live LsmDB iterators holding this reader; `retired`
    marks it dropped by compaction.  A retired reader is closed by the
    DB as soon as its pin count reaches zero — deterministic fd
    release instead of relying on CPython refcounting GC."""

    def __init__(self, path: Path):
        self.path = path
        self.pins = 0
        self.retired = False
        self.f = open(path, "rb")
        self.f.seek(0, os.SEEK_END)
        end = self.f.tell()
        self.f.seek(end - 20)
        idx_off, idx_len, idx_crc = struct.unpack("<QII", self.f.read(16))
        if self.f.read(4) != _SST_MAGIC:
            raise ValueError(f"bad sst footer magic: {path}")
        self.f.seek(idx_off)
        payload = self.f.read(idx_len)
        if _crc.crc32c(payload, 0xFFFFFFFF) != idx_crc:
            raise ValueError(f"sst index crc mismatch: {path}")
        self.block_keys: list[bytes] = []
        self.block_offs: list[int] = []
        pos = 0
        while pos < len(payload):
            klen, off = struct.unpack_from("<HQ", payload, pos)
            pos += 10
            self.block_keys.append(payload[pos:pos + klen])
            pos += klen
            self.block_offs.append(off)
        self._end_of_blocks = idx_off

    def _read_block(self, bi: int) -> list[tuple[bytes, bytes | None]]:
        # pread: no shared seek state, so concurrent iterators on the
        # same reader can't corrupt each other's position
        off = self.block_offs[bi]
        head = os.pread(self.f.fileno(), 8, off)
        ln, crc = struct.unpack("<II", head)
        payload = os.pread(self.f.fileno(), ln, off + 8)
        if _crc.crc32c(payload, 0xFFFFFFFF) != crc:
            raise ValueError(
                f"sst block crc mismatch: {self.path} block {bi}")
        out = []
        pos = 0
        while pos < len(payload):
            klen, vlen = struct.unpack_from("<HI", payload, pos)
            pos += 6
            key = payload[pos:pos + klen]
            pos += klen
            if vlen == _TOMBSTONE:
                out.append((key, None))
            else:
                out.append((key, payload[pos:pos + vlen]))
                pos += vlen
        return out

    def get(self, key: bytes):
        """-> (found, value|None): distinguishes tombstone from miss."""
        bi = bisect.bisect_right(self.block_keys, key) - 1
        if bi < 0:
            return False, None
        for k, v in self._read_block(bi):
            if k == key:
                return True, v
        return False, None

    def scan(self, start: bytes = b""):
        """Yield (key, value|None) for keys >= start, in order."""
        bi = max(bisect.bisect_right(self.block_keys, start) - 1, 0)
        for b in range(bi, len(self.block_keys)):
            for k, v in self._read_block(b):
                if k >= start:
                    yield k, v

    def close(self) -> None:
        self.f.close()


class _RangeScan:
    """Iterator over a merged range scan holding SSTReader pins.

    A plain generator's `finally` can NOT carry the unpin: pins are
    taken eagerly (the snapshot — and the readers' liveness — is fixed
    at iterate_range() call time), but closing a never-started
    generator skips its try block entirely, so an iterator that is
    created and then abandoned would leak its pins forever.  This
    class releases exactly once on whichever comes first: exhaustion,
    explicit close(), or __del__ (refcount-prompt on CPython; on other
    runtimes LsmDB.close() still sweeps parked readers)."""

    def __init__(self, db, sources, end, pinned):
        self._db = db
        self._pinned = pinned
        self._gen = db._merge(sources, end)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._release()
            raise

    def close(self) -> None:
        self._gen.close()
        self._release()

    def _release(self) -> None:
        pinned, self._pinned = self._pinned, None
        if pinned:
            self._db._unpin(pinned)

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self._release()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# ----------------------------------------------------------------------------
# LsmDB
# ----------------------------------------------------------------------------

class LsmDB(KeyValueDB):
    """Leveled LSM store behind the KeyValueDB interface."""

    def __init__(self, path: str, memtable_bytes: int = 4 << 20,
                 l0_max_files: int = 4, base_level_bytes: int = 32 << 20,
                 level_multiplier: int = 10, block_size: int = 4096,
                 target_file_bytes: int | None = None):
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.memtable_bytes = memtable_bytes
        self.l0_max_files = l0_max_files
        self.base_level_bytes = base_level_bytes
        self.level_multiplier = level_multiplier
        self.block_size = block_size
        self.target_file_bytes = target_file_bytes or 2 * memtable_bytes
        self._lock = threading.RLock()
        self._mem: dict[bytes, bytes | None] = {}   # None = tombstone
        self._mem_bytes = 0
        # manifest state: levels[0] newest-last; levels[n>=1] sorted by
        # min key, non-overlapping
        self._levels: list[list[dict]] = [[]]
        self._readers: dict[str, SSTReader] = {}
        self._retired: list[SSTReader] = []   # dropped by compaction,
        # still pinned by live iterators; closed on last unpin/close
        self._next_seq = 1
        # observability: compaction I/O must stay bounded (the whole
        # point vs LogDB) — tests assert on these
        self.stats = {"flushes": 0, "compactions": 0,
                      "compact_bytes_in": 0, "compact_bytes_out": 0,
                      "max_compact_bytes": 0}
        self._load_manifest()
        # distinct WAL name: LogDB's wal.log shares the frame header but
        # carries JSON bodies — open_kv migrates those, and the name
        # split guarantees the two formats can never be cross-parsed
        self._wal_path = self.dir / "wal.lsm"
        self._replay_wal()
        self._wal_f = open(self._wal_path, "ab")

    # -- manifest / recovery ------------------------------------------------

    def _manifest_path(self) -> Path:
        return self.dir / "MANIFEST.json"

    def _load_manifest(self) -> None:
        mp = self._manifest_path()
        if not mp.exists():
            return
        m = json.loads(mp.read_text())
        self._next_seq = m["next_seq"]
        self._levels = []
        for files in m["levels"]:
            lvl = []
            for fe in files:
                p = self.dir / fe["name"]
                if not p.exists():      # crashed mid-compaction: the
                    continue            # manifest write is the commit
                # decoded bounds cached once (underscore keys stay out
                # of the manifest) — get() binary-searches these on
                # every read, and hex-decoding per lookup would sit on
                # the hottest metadata path
                fe["_min"] = bytes.fromhex(fe["min"])
                fe["_max"] = bytes.fromhex(fe["max"])
                lvl.append(fe)
                self._readers[fe["name"]] = SSTReader(p)
            self._levels.append(lvl)
        if not self._levels:
            self._levels = [[]]
        self._gc_unreferenced()

    def _write_manifest(self) -> None:
        m = {"next_seq": self._next_seq,
             "levels": [[{k: v for k, v in fe.items()
                          if not k.startswith("_")} for fe in lvl]
                        for lvl in self._levels]}
        tmp = self._manifest_path().with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump(m, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path())
        _fsync_dir(self.dir)

    def _gc_unreferenced(self) -> None:
        live = {fe["name"] for lvl in self._levels for fe in lvl}
        for p in self.dir.glob("*.sst"):
            if p.name not in live:
                p.unlink()

    def _replay_wal(self) -> None:
        if not self._wal_path.exists():
            return
        good = 0
        with open(self._wal_path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                ln, crc = struct.unpack("<II", head)
                body = f.read(ln)
                if len(body) < ln or \
                        _crc.crc32c(body, 0xFFFFFFFF) != crc:
                    break               # torn tail: stop replay
                good = f.tell()
                pos = 0
                while pos < len(body):
                    klen, vlen = struct.unpack_from("<HI", body, pos)
                    pos += 6
                    key = body[pos:pos + klen]
                    pos += klen
                    if vlen == _TOMBSTONE:
                        self._mem_insert(key, None)
                    else:
                        self._mem_insert(key, body[pos:pos + vlen])
                        pos += vlen
        if good < self._wal_path.stat().st_size:
            # drop the torn bytes BEFORE appending again: otherwise the
            # next restart's replay stops at the old tear and loses
            # fsync-acked batches written after it
            with open(self._wal_path, "r+b") as f:
                f.truncate(good)
                f.flush()
                os.fsync(f.fileno())

    # -- memtable -----------------------------------------------------------

    def _mem_insert(self, key: bytes, value: bytes | None) -> None:
        old = self._mem.get(key)
        if key in self._mem:
            self._mem_bytes -= len(key) + (len(old) if old else 0)
        self._mem[key] = value
        self._mem_bytes += len(key) + (len(value) if value else 0)

    # -- public API ---------------------------------------------------------

    def get(self, key):
        key = bytes(key)
        with self._lock:
            if key in self._mem:
                return self._mem[key]
            for fe in reversed(self._levels[0]):     # newest L0 first
                found, v = self._readers[fe["name"]].get(key)
                if found:
                    return v
            for lvl in self._levels[1:]:
                fi = self._find_file(lvl, key)
                if fi is not None:
                    found, v = self._readers[lvl[fi]["name"]].get(key)
                    if found:
                        return v
            return None

    @staticmethod
    def _find_file(lvl: list[dict], key: bytes) -> int | None:
        """Binary search a non-overlapping level for the file covering
        key (cached decoded bounds — no per-read hex work)."""
        i = bisect.bisect_right(lvl, key, key=lambda fe: fe["_min"]) - 1
        if i >= 0 and key <= lvl[i]["_max"]:
            return i
        return None

    MAX_KEY = 0xFFFF     # keys pack as <H in the WAL/SST record format

    def submit(self, batch: WriteBatch, sync: bool = True) -> None:
        for op in batch.ops:
            if len(op[1]) > self.MAX_KEY:
                raise ValueError(
                    f"LsmDB key too long ({len(op[1])} > "
                    f"{self.MAX_KEY} bytes)")
        body = bytearray()
        for op in batch.ops:
            if op[0] == "set":
                body += struct.pack("<HI", len(op[1]), len(op[2]))
                body += op[1] + op[2]
            else:
                body += struct.pack("<HI", len(op[1]), _TOMBSTONE)
                body += op[1]
        payload = bytes(body)
        head = struct.pack("<II", len(payload),
                           _crc.crc32c(payload, 0xFFFFFFFF))
        with self._lock:
            self._wal_f.write(head + payload)
            self._wal_f.flush()
            if sync:
                os.fsync(self._wal_f.fileno())
            for op in batch.ops:
                self._mem_insert(op[1],
                                 op[2] if op[0] == "set" else None)
            if self._mem_bytes >= self.memtable_bytes:
                self._flush_locked()
                self._maybe_compact_locked()

    def compact(self) -> None:
        """Flush the memtable and fully settle level budgets."""
        with self._lock:
            if self._mem:
                self._flush_locked()
            self._maybe_compact_locked()

    @staticmethod
    def _prefix_end(prefix: bytes) -> bytes | None:
        """Smallest key > every key with this prefix (carry through
        trailing 0xff bytes); None = unbounded."""
        p = bytearray(prefix)
        while p and p[-1] == 0xFF:
            p.pop()
        if not p:
            return None
        p[-1] += 1
        return bytes(p)

    def iterate(self, prefix=b""):
        prefix = bytes(prefix)
        end = self._prefix_end(prefix) if prefix else None
        return self.iterate_range(prefix, end)

    def iterate_range(self, start: bytes = b"", end: bytes | None = None):
        """Merged range scan [start, end).  Consistent over the version
        at call time (the snapshot is taken HERE, not at first next()):
        every SSTReader the scan touches is pinned, so compaction can
        retire files underneath without disturbing the scan, and the
        retired reader's fd closes deterministically when the last
        pinning iterator finishes (generator exhaustion or .close())."""
        with self._lock:
            sources = []
            pinned: list[SSTReader] = []

            def _pin(name: str) -> SSTReader:
                r = self._readers[name]
                r.pins += 1
                pinned.append(r)
                return r

            # recency rank: memtable 0, L0 newest 1.., deeper levels last
            mem_items = sorted(
                (k, v) for k, v in self._mem.items() if k >= start)
            sources.append((0, iter(mem_items)))
            rank = 1
            for fe in reversed(self._levels[0]):
                sources.append((rank, _pin(fe["name"]).scan(start)))
                rank += 1
            for lvl in self._levels[1:]:
                its = [_pin(fe["name"]).scan(start)
                       for fe in lvl if fe["_max"] >= start]
                for it in its:
                    sources.append((rank, it))
                rank += 1

        return _RangeScan(self, sources, end, pinned)

    @staticmethod
    def _merge(sources, end):
        prev = None
        for k, v in LsmDB._merge_raw(sources):
            if end is not None and k >= end:
                return      # heap head is the global min: all done
            if k == prev:
                continue                 # older duplicate: shadowed
            prev = k
            if v is not None:
                yield k, v

    def close(self) -> None:
        with self._lock:
            self._wal_f.close()
            for r in self._readers.values():
                r.close()
            # compaction-retired readers kept alive for in-flight
            # iterators: close() is terminal, release them all
            for r in self._retired:
                r.close()
            self._retired.clear()

    def _unpin(self, readers: list[SSTReader]) -> None:
        """Iterator teardown: drop pins; close retired readers whose
        last pin just left (the deterministic half of the fd lifecycle
        — see SSTReader.pins)."""
        with self._lock:
            for r in readers:
                r.pins -= 1
                if r.retired and r.pins == 0:
                    r.close()
                    try:
                        self._retired.remove(r)
                    except ValueError:
                        pass

    # -- flush / compaction -------------------------------------------------

    def _new_sst(self, level: int,
                 items) -> list[dict]:
        """Write items (sorted (k, v|None)) into one or more SSTs split
        at target_file_bytes; returns file entries."""
        out = []
        w = None
        for k, v in items:
            if w is None:
                name = f"sst_{level}_{self._next_seq:08d}.sst"
                self._next_seq += 1
                w = SSTWriter(self.dir / name, self.block_size)
            w.add(k, v)
            if w.f.tell() >= self.target_file_bytes:
                w.finish()
                out.append(self._entry(w))
                w = None
        if w is not None:
            w.finish()
            if w.count:
                out.append(self._entry(w))
            else:
                (w.path).unlink()
        return out

    def _entry(self, w: SSTWriter) -> dict:
        self._readers[w.path.name] = SSTReader(w.path)
        size = w.path.stat().st_size
        self.stats["compact_bytes_out"] += size
        return {"name": w.path.name, "min": w.min_key.hex(),
                "max": w.max_key.hex(), "count": w.count, "bytes": size,
                "_min": w.min_key, "_max": w.max_key}

    def _flush_locked(self) -> None:
        items = sorted(self._mem.items())
        files = self._new_sst(0, items)
        self._levels[0].extend(files)
        self._write_manifest()           # commit point
        self._mem.clear()
        self._mem_bytes = 0
        self._wal_f.close()
        self._wal_f = open(self._wal_path, "wb")
        self._wal_f.flush()
        os.fsync(self._wal_f.fileno())
        self.stats["flushes"] += 1

    def _level_budget(self, level: int) -> int:
        return self.base_level_bytes * \
            self.level_multiplier ** (level - 1)

    def _maybe_compact_locked(self) -> None:
        while len(self._levels[0]) > self.l0_max_files:
            self._compact_level_locked(0, None)
        lvl = 1
        while lvl < len(self._levels):
            total = sum(fe["bytes"] for fe in self._levels[lvl])
            if total > self._level_budget(lvl):
                # push the file with the most overlap-free room —
                # oldest (lowest seq) keeps it deterministic
                victim = min(range(len(self._levels[lvl])),
                             key=lambda i: self._levels[lvl][i]["name"])
                self._compact_level_locked(lvl, victim)
            else:
                lvl += 1

    def _compact_level_locked(self, level: int,
                              victim: int | None) -> None:
        """Merge inputs from `level` (all of L0, or one victim file)
        with the overlapping files of level+1 into level+1."""
        if level == 0:
            up_files = list(self._levels[0])
        else:
            up_files = [self._levels[level][victim]]
        lo = min(fe["_min"] for fe in up_files)
        hi = max(fe["_max"] for fe in up_files)
        if len(self._levels) <= level + 1:
            self._levels.append([])
        down = self._levels[level + 1]
        overlap = [fe for fe in down
                   if not (fe["_max"] < lo or fe["_min"] > hi)]
        bottommost = (level + 2 >= len(self._levels) or
                      not any(self._levels[level + 2:]))
        # merge newest-first ranks: L0 newest-last in list
        sources = []
        rank = 0
        for fe in reversed(up_files):
            sources.append((rank, self._readers[fe["name"]].scan()))
            rank += 1
        for fe in overlap:
            sources.append((rank, self._readers[fe["name"]].scan()))
        rank += 1
        bytes_in = sum(fe["bytes"] for fe in up_files + overlap)

        def merged():
            for k, v in self._merge_raw(sources):
                if v is None and bottommost:
                    continue             # tombstone reaches bedrock
                yield k, v

        new_files = self._new_sst(level + 1, merged())
        # install: remove inputs, insert outputs sorted by min key
        if level == 0:
            self._levels[0] = []
        else:
            del self._levels[level][victim]
        keep = [fe for fe in down if fe not in overlap]
        self._levels[level + 1] = sorted(
            keep + new_files, key=lambda fe: fe["min"])
        self._write_manifest()           # commit point
        for fe in up_files + overlap:
            # retire the reader and unlink the file; the inode stays
            # alive behind the open fd, so in-flight scans finish
            # against the retired file.  Unpinned readers close NOW;
            # pinned ones park in _retired and close on last unpin (or
            # LsmDB.close()) — no fd accumulation on non-refcounting
            # runtimes across long compaction histories
            rd = self._readers.pop(fe["name"], None)
            (self.dir / fe["name"]).unlink(missing_ok=True)
            if rd is not None:
                rd.retired = True
                if rd.pins == 0:
                    rd.close()
                else:
                    self._retired.append(rd)
        self.stats["compactions"] += 1
        self.stats["compact_bytes_in"] += bytes_in
        self.stats["max_compact_bytes"] = max(
            self.stats["max_compact_bytes"], bytes_in)

    @staticmethod
    def _merge_raw(sources):
        """Merge (rank, iterator) sources keeping newest (lowest rank)
        per key; yields tombstones (v=None) too."""
        heap = []
        for rank, it in sources:
            for k, v in it:
                heap.append((k, rank, v, it))
                break
        heapq.heapify(heap)
        prev = None
        while heap:
            k, rank, v, it = heapq.heappop(heap)
            for nk, nv in it:
                heapq.heappush(heap, (nk, rank, nv, it))
                break
            if k == prev:
                continue
            prev = k
            yield k, v
