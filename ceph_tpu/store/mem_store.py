"""MemStore: in-memory ObjectStore (reference src/os/memstore/MemStore.h:30).

The fake backend unit/standalone tests run against for speed; also the
default store of the dev cluster (vstart analog).  Thread-safe; commits
are immediate (fsync-free), callbacks fire synchronously in queue order.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np

from ..osd.types import ghobject_t, spg_t
from . import object_store as os_
from .object_store import ObjectStore, Transaction


@dataclass
class _Object:
    data: bytearray = field(default_factory=bytearray)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    omap: dict[bytes, bytes] = field(default_factory=dict)
    omap_header: bytes = b""

    def clone(self) -> "_Object":
        return _Object(bytearray(self.data), dict(self.xattrs),
                       dict(self.omap), self.omap_header)


class MemStore(ObjectStore):
    def __init__(self) -> None:
        self._colls: dict[spg_t, dict[ghobject_t, _Object]] = {}
        self._lock = threading.RLock()
        self._mounted = False

    # -- lifecycle ----------------------------------------------------------

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    # -- collections --------------------------------------------------------

    def create_collection(self, cid: spg_t) -> None:
        with self._lock:
            self._colls.setdefault(cid, {})

    def remove_collection(self, cid: spg_t) -> None:
        with self._lock:
            self._colls.pop(cid, None)

    def list_collections(self) -> list[spg_t]:
        with self._lock:
            return sorted(self._colls)

    def collection_exists(self, cid: spg_t) -> bool:
        with self._lock:
            return cid in self._colls

    # -- transactions -------------------------------------------------------

    def queue_transactions(self, cid: spg_t,
                           txns: Iterable[Transaction]) -> None:
        callbacks = []
        with self._lock:
            coll = self._colls.get(cid)
            if coll is None:
                raise KeyError(f"no collection {cid}")
            for t in txns:
                for op in t.ops:
                    self._apply(coll, op)
                callbacks.extend(t.on_commit)
        for cb in callbacks:
            cb()

    def _obj(self, coll, oid) -> _Object:
        o = coll.get(oid)
        if o is None:
            o = coll[oid] = _Object()
        return o

    def _apply(self, coll, op) -> None:
        if isinstance(op, os_.OpTouch):
            self._obj(coll, op.oid)
        elif isinstance(op, os_.OpWrite):
            o = self._obj(coll, op.oid)
            end = op.offset + op.data.size
            if len(o.data) < end:
                o.data.extend(bytes(end - len(o.data)))
            o.data[op.offset:end] = op.data.tobytes()
        elif isinstance(op, os_.OpZero):
            o = self._obj(coll, op.oid)
            end = op.offset + op.length
            if len(o.data) < end:
                o.data.extend(bytes(end - len(o.data)))
            o.data[op.offset:end] = bytes(op.length)
        elif isinstance(op, os_.OpTruncate):
            o = self._obj(coll, op.oid)
            if op.size < len(o.data):
                del o.data[op.size:]
            else:
                o.data.extend(bytes(op.size - len(o.data)))
        elif isinstance(op, os_.OpRemove):
            coll.pop(op.oid, None)
        elif isinstance(op, os_.OpSetAttrs):
            self._obj(coll, op.oid).xattrs.update(op.attrs)
        elif isinstance(op, os_.OpRmAttr):
            self._obj(coll, op.oid).xattrs.pop(op.name, None)
        elif isinstance(op, os_.OpClone):
            src = coll.get(op.src)
            if src is not None:
                coll[op.dst] = src.clone()
        elif isinstance(op, os_.OpRename):
            src = coll.pop(op.src, None)
            if src is not None:
                coll[op.dst] = src
        elif isinstance(op, os_.OpOmapSet):
            self._obj(coll, op.oid).omap.update(op.kv)
        elif isinstance(op, os_.OpOmapRmKeys):
            o = self._obj(coll, op.oid)
            for k in op.keys:
                o.omap.pop(k, None)
        elif isinstance(op, os_.OpOmapClear):
            o = self._obj(coll, op.oid)
            o.omap.clear()
            o.omap_header = b""
        elif isinstance(op, os_.OpOmapSetHeader):
            self._obj(coll, op.oid).omap_header = op.data
        else:
            raise TypeError(f"unknown transaction op {op!r}")

    # -- reads --------------------------------------------------------------

    def _get(self, cid, oid) -> _Object:
        coll = self._colls.get(cid)
        if coll is None:
            raise KeyError(f"no collection {cid}")
        o = coll.get(oid)
        if o is None:
            raise KeyError(f"no object {oid} in {cid}")
        return o

    def read(self, cid, oid, offset=0, length=None) -> np.ndarray:
        with self._lock:
            o = self._get(cid, oid)
            end = len(o.data) if length is None else min(
                len(o.data), offset + length)
            return np.frombuffer(bytes(o.data[offset:end]), dtype=np.uint8)

    def stat(self, cid, oid) -> int:
        with self._lock:
            return len(self._get(cid, oid).data)

    def exists(self, cid, oid) -> bool:
        with self._lock:
            coll = self._colls.get(cid)
            return coll is not None and oid in coll

    def getattr(self, cid, oid, name) -> bytes:
        with self._lock:
            return self._get(cid, oid).xattrs[name]

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).xattrs)

    def omap_get(self, cid, oid) -> dict[bytes, bytes]:
        with self._lock:
            return dict(self._get(cid, oid).omap)

    def omap_get_header(self, cid, oid) -> bytes:
        with self._lock:
            return self._get(cid, oid).omap_header

    def list_objects(self, cid) -> list[ghobject_t]:
        with self._lock:
            coll = self._colls.get(cid)
            if coll is None:
                raise KeyError(f"no collection {cid}")
            return sorted(coll)
