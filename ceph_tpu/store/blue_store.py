"""BlueStore-role raw-block ObjectStore.

Re-expresses the reference's production store architecture
(src/os/bluestore/BlueStore.h): object data lives on a raw block
"device" (one big file here) carved by an extent allocator, object
metadata (onodes) lives in a KV store, and writes follow BlueStore's
two paths:

* BIG / COW writes — the new object payload is written to FRESHLY
  allocated extents first, then the onode flips to them in one atomic
  KV commit and the old extents are released.  A crash before the KV
  commit leaves the old blob fully intact: no WAL, no double-write of
  data — the core BlueStore trick.
* SMALL in-place overwrites — the deferred-write machine
  (BlueStore.h:1504 STATE_DEFERRED_*): the payload is journaled INSIDE
  the same KV commit (a "D/" row) and applied to the block file after;
  mount replays unapplied rows.  Small overwrites cost one KV write +
  one in-place block write instead of a whole-blob COW.

Integrity at rest (bluestore_types.h:450 blob csum_data): every blob
carries crc32c per 4 KiB csum block, verified on EVERY read — bitrot
in the block file surfaces as EIO instead of silently corrupt data
(scrub repairs it from the other shards).  Blobs compress at rest
through the compressor subsystem when beneficial (reference blob
compression + min_alloc gating).

The allocator's free map is rebuilt from the onodes at mount (see
allocator.py).  Omap/xattrs ride the KV exactly like FileStore's.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Iterable

import numpy as np

from ..common import crc32c as _crc
from ..osd.types import ghobject_t, hobject_t, spg_t
from . import object_store as os_
from .allocator import Allocator
from .file_store import _esc
from .kv import KeyValueDB, WriteBatch, open_kv
from .object_store import ObjectStore, Transaction

MIN_ALLOC = 4096
CSUM_BLOCK = 4096
DEFERRED_MAX = 64 * 1024      # in-place path for writes <= this
COMPRESS_MIN_RATIO = 0.875    # keep compressed only if <= 7/8 of raw


def _csums(data: bytes) -> list[int]:
    return [_crc.crc32c(data[i:i + CSUM_BLOCK], 0xFFFFFFFF)
            for i in range(0, max(len(data), 1), CSUM_BLOCK)]


class BlueStore(ObjectStore):
    def __init__(self, path: str, compression: str | None = None):
        self.root = Path(path)
        self.kv: KeyValueDB | None = None
        self._lock = threading.RLock()
        self._block_f = None
        self._mounted = False
        self.alloc = Allocator(0, MIN_ALLOC)
        self._deferred_seq = 0
        # read-your-writes overlay for the transaction being prepared:
        # ops later in one txn (clone-after-setattr, double write) must
        # see the batch's pending mutations, which are not in the KV
        # until the single atomic submit
        self._overlay: dict | None = None
        self._content_overlay: dict | None = None
        self._txn_allocated: list | None = None
        self._wrote_blocks = False
        self.compression = compression
        self._compressor = None
        if compression:
            from ..compressor import create
            self._compressor = create(compression)

    # -- key scheme (FileStore-compatible shape, distinct kinds) ------------

    @staticmethod
    def _ckey(cid: spg_t) -> bytes:
        return f"C/{cid.pgid.pool}/{cid.pgid.seed}/{cid.shard}".encode()

    @staticmethod
    def _okey(cid: spg_t, oid: ghobject_t, kind: str,
              extra: str = "") -> bytes:
        h = oid.hobj
        return (f"{kind}/{cid.pgid.pool}/{cid.pgid.seed}/{cid.shard}/"
                f"{_esc(h.name)}/{_esc(h.key)}/{h.snap}/"
                f"{oid.generation}/{oid.shard}/{extra}").encode()

    # -- lifecycle ----------------------------------------------------------

    def mount(self) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        self.kv = open_kv(str(self.root / "kv"))
        block = self.root / "block"
        if not block.exists():
            block.write_bytes(b"")
        self._block_f = os.open(block, os.O_RDWR)
        # rebuild the allocator from authoritative onode metadata
        size = os.fstat(self._block_f).st_size
        self.alloc = Allocator(size, MIN_ALLOC)
        for _k, v in self.kv.iterate(b"N/"):
            onode = json.loads(v.decode())
            for off, length in onode["blob"]["extents"]:
                self.alloc.mark_used(off, length)
        self._replay_deferred()
        self._mounted = True

    def umount(self) -> None:
        with self._lock:
            if self._block_f is not None:
                os.fsync(self._block_f)
                os.close(self._block_f)
                self._block_f = None
            if self.kv:
                self.kv.compact()
                self.kv.close()
                self.kv = None
            self._mounted = False

    def _replay_deferred(self) -> None:
        """Apply deferred writes that committed in the KV but didn't
        reach the block file before a crash (idempotent: in-place
        writes of the same bytes)."""
        done = WriteBatch()
        for k, v in self.kv.iterate(b"D/"):
            rec = json.loads(v.decode())
            data = bytes.fromhex(rec["hex"])
            pos = 0
            for off, length in rec["extents"]:
                self._pwrite(off, data[pos:pos + length])
                pos += length
            done.rm(k)
            self._deferred_seq = max(self._deferred_seq,
                                     int(k.decode().split("/")[1]) + 1)
        if done.ops:
            os.fsync(self._block_f)
            self.kv.submit(done, sync=True)

    # -- block device helpers -----------------------------------------------

    def _pwrite(self, off: int, data: bytes) -> None:
        os.pwrite(self._block_f, data, off)

    def _pread(self, off: int, length: int) -> bytes:
        data = os.pread(self._block_f, length, off)
        return data.ljust(length, b"\x00")

    # -- onodes --------------------------------------------------------------

    def _kv_get(self, key: bytes) -> bytes | None:
        if self._overlay is not None and key in self._overlay:
            return self._overlay[key]
        return self.kv.get(key)

    def _kv_iter(self, prefix: bytes):
        rows = dict(self.kv.iterate(prefix))
        if self._overlay:
            for k, v in self._overlay.items():
                if k.startswith(prefix):
                    if v is None:
                        rows.pop(k, None)
                    else:
                        rows[k] = v
        return sorted(rows.items())

    def _bset(self, batch: WriteBatch, key: bytes, val: bytes) -> None:
        batch.set(key, val)
        if self._overlay is not None:
            self._overlay[key] = bytes(val)

    def _brm(self, batch: WriteBatch, key: bytes) -> None:
        batch.rm(key)
        if self._overlay is not None:
            self._overlay[key] = None

    def _onode(self, cid, oid) -> dict | None:
        raw = self._kv_get(self._okey(cid, oid, "N"))
        return json.loads(raw.decode()) if raw is not None else None

    def _read_blob(self, blob: dict) -> bytes:
        """Read + VERIFY a whole blob; raises IOError on csum mismatch
        (at-rest bitrot must never read back as data)."""
        stored = bytearray()
        for off, length in blob["extents"]:
            stored += self._pread(off, length)
        stored = bytes(stored[:blob["stored"]])
        for i, want in enumerate(blob["csum"]):
            got = _crc.crc32c(stored[i * CSUM_BLOCK:(i + 1) * CSUM_BLOCK],
                              0xFFFFFFFF)
            if got != want:
                raise IOError(
                    f"bluestore csum mismatch in csum block {i} "
                    f"(at-rest corruption)")
        if blob.get("alg"):
            from ..compressor import create
            stored = create(blob["alg"]).decompress(stored)
        return stored[:blob["raw"]]

    def _content(self, cid, oid) -> bytes:
        onode = self._onode(cid, oid)
        if onode is None:
            raise KeyError(f"no object {oid} in {cid}")
        okey = self._okey(cid, oid, "N")
        if self._content_overlay is not None and \
                okey in self._content_overlay:
            raw = self._content_overlay[okey]
            return raw.ljust(onode["size"], b"\x00")[:onode["size"]]
        if not onode["blob"]["extents"] and onode["blob"]["raw"] == 0:
            return b""
        return self._read_blob(onode["blob"]).ljust(onode["size"],
                                                    b"\x00")[:onode["size"]]

    def _write_blob(self, data: bytes) -> dict:
        """COW path: fresh extents + csums (+ compression when it
        pays); the caller commits the onode pointing here atomically."""
        raw_len = len(data)
        alg = None
        stored = data
        if self._compressor is not None and raw_len >= MIN_ALLOC:
            try:
                comp = self._compressor.compress(data)
                if len(comp) <= raw_len * COMPRESS_MIN_RATIO:
                    stored = comp
                    alg = self.compression
            except Exception:  # noqa: BLE001 - store uncompressed
                pass
        extents = self.alloc.allocate(max(len(stored), 1))
        if self._txn_allocated is not None:
            self._txn_allocated.extend(extents)
        self._wrote_blocks = True
        pos = 0
        for off, length in extents:
            self._pwrite(off, stored[pos:pos + length].ljust(length,
                                                             b"\x00"))
            pos += length
        return {"extents": extents, "stored": len(stored),
                "csum": _csums(stored), "raw": raw_len, "alg": alg}

    def _put_object(self, cid, oid, data: bytes, batch: WriteBatch,
                    released: list) -> None:
        old = self._onode(cid, oid)
        if old is not None:
            released.extend(old["blob"]["extents"])
        blob = self._write_blob(data)
        okey = self._okey(cid, oid, "N")
        self._bset(batch, okey, json.dumps(
            {"size": len(data), "blob": blob},
            separators=(",", ":")).encode())
        if self._content_overlay is not None:
            # supersede any earlier deferred content for this object
            self._content_overlay[okey] = bytes(data)

    def _try_deferred(self, cid, oid, op, batch: WriteBatch) -> bool:
        """Small aligned in-place overwrite within the existing
        uncompressed blob: journal payload in the KV commit, apply
        after (deferred-write machine)."""
        onode = self._onode(cid, oid)
        if onode is None or onode["blob"].get("alg"):
            return False
        end = op.offset + op.data.size
        if op.data.size > DEFERRED_MAX or end > onode["size"] or \
                onode["blob"]["raw"] != onode["blob"]["stored"]:
            return False
        # the touched csum blocks must be recomputed: read the blob,
        # patch, recompute only those blocks.  Earlier deferred writes
        # in this txn live in the content overlay, not on the device.
        okey = self._okey(cid, oid, "N")
        try:
            if self._content_overlay is not None and \
                    okey in self._content_overlay:
                content = bytearray(self._content_overlay[okey])
            else:
                content = bytearray(self._read_blob(onode["blob"]))
        except IOError:
            return False
        content[op.offset:end] = op.data.tobytes()
        first = op.offset // CSUM_BLOCK
        last = (end - 1) // CSUM_BLOCK
        for i in range(first, last + 1):
            onode["blob"]["csum"][i] = _crc.crc32c(
                bytes(content[i * CSUM_BLOCK:(i + 1) * CSUM_BLOCK]),
                0xFFFFFFFF)
        # map the logical range onto physical extents
        phys: list[tuple[int, int]] = []
        loff = 0
        for eoff, elen in onode["blob"]["extents"]:
            s = max(op.offset, loff)
            e = min(end, loff + elen)
            if s < e:
                phys.append((eoff + (s - loff), e - s))
            loff += elen
        seq = self._deferred_seq
        self._deferred_seq += 1
        self._bset(batch, f"D/{seq:016d}".encode(), json.dumps(
            {"extents": phys,
             "hex": op.data.tobytes().hex()}).encode())
        self._bset(batch, self._okey(cid, oid, "N"), json.dumps(
            onode, separators=(",", ":")).encode())
        self._pending_deferred.append((f"D/{seq:016d}".encode(), phys,
                                       op.data.tobytes()))
        if self._content_overlay is not None:
            self._content_overlay[okey] = bytes(content)
        return True

    # -- transactions -------------------------------------------------------

    def queue_transactions(self, cid: spg_t,
                           txns: Iterable[Transaction]) -> None:
        if not self._mounted:
            raise RuntimeError("store not mounted")
        callbacks = []
        with self._lock:
            if self.kv.get(self._ckey(cid)) is None:
                raise KeyError(f"no collection {cid}")
            batch = WriteBatch()
            released: list = []
            self._pending_deferred: list = []
            self._overlay = {}
            self._content_overlay = {}
            self._txn_allocated = []
            self._wrote_blocks = False
            try:
                for t in txns:
                    for op in t.ops:
                        self._prep(cid, op, batch, released)
                    callbacks.extend(t.on_commit)
            except Exception:
                # the batch dies with the exception: give back every
                # extent it allocated or the space leaks until remount
                self.alloc.release(self._txn_allocated)
                raise
            finally:
                self._overlay = None
                self._content_overlay = None
                self._txn_allocated = None
            # COW blob data must be DURABLE before the onode that
            # references it commits — otherwise a power loss after the
            # sync'd KV commit leaves a durable onode pointing at
            # never-persisted bytes (acked write lost as EIO)
            if self._wrote_blocks:
                os.fsync(self._block_f)
            self.kv.submit(batch, sync=True)
            # apply deferred in-place writes post-commit; the journal
            # rows are retired only after the block writes are durable
            # (same ordering _replay_deferred uses)
            if self._pending_deferred:
                done = WriteBatch()
                for key, phys, data in self._pending_deferred:
                    pos = 0
                    for off, length in phys:
                        self._pwrite(off, data[pos:pos + length])
                        pos += length
                    done.rm(key)
                os.fsync(self._block_f)
                self.kv.submit(done, sync=False)
            self.alloc.release(released)
        for cb in callbacks:
            cb()

    def _prep(self, cid, op, batch: WriteBatch, released: list) -> None:
        if isinstance(op, os_.OpTouch):
            if self._onode(cid, op.oid) is None:
                self._put_object(cid, op.oid, b"", batch, released)
        elif isinstance(op, os_.OpWrite):
            if op.data.size and self._try_deferred(cid, op.oid, op,
                                                   batch):
                return
            try:
                content = bytearray(self._content(cid, op.oid))
            except KeyError:
                content = bytearray()
            end = op.offset + op.data.size
            if len(content) < end:
                content.extend(bytes(end - len(content)))
            content[op.offset:end] = op.data.tobytes()
            self._put_object(cid, op.oid, bytes(content), batch,
                             released)
        elif isinstance(op, os_.OpZero):
            try:
                content = bytearray(self._content(cid, op.oid))
            except KeyError:
                content = bytearray()
            end = op.offset + op.length
            if len(content) < end:
                content.extend(bytes(end - len(content)))
            content[op.offset:end] = bytes(op.length)
            self._put_object(cid, op.oid, bytes(content), batch,
                             released)
        elif isinstance(op, os_.OpTruncate):
            try:
                content = bytearray(self._content(cid, op.oid))
            except KeyError:
                content = bytearray()
            if op.size <= len(content):
                content = content[:op.size]
            else:
                content.extend(bytes(op.size - len(content)))
            self._put_object(cid, op.oid, bytes(content), batch,
                             released)
        elif isinstance(op, os_.OpRemove):
            onode = self._onode(cid, op.oid)
            if onode is not None:
                released.extend(onode["blob"]["extents"])
            self._brm(batch, self._okey(cid, op.oid, "N"))
            self._brm(batch, self._okey(cid, op.oid, "H"))
            for kind in ("A", "O"):
                for k, _ in list(self._kv_iter(
                        self._okey(cid, op.oid, kind))):
                    self._brm(batch, k)
        elif isinstance(op, os_.OpSetAttrs):
            if self._onode(cid, op.oid) is None:
                self._put_object(cid, op.oid, b"", batch, released)
            for k, v in op.attrs.items():
                self._bset(batch, self._okey(cid, op.oid, "A", _esc(k)), v)
        elif isinstance(op, os_.OpRmAttr):
            self._brm(batch, self._okey(cid, op.oid, "A", _esc(op.name)))
        elif isinstance(op, os_.OpClone):
            try:
                content = self._content(cid, op.src)
            except KeyError:
                return
            dst_old = self._onode(cid, op.dst)
            if dst_old is not None:
                released.extend(dst_old["blob"]["extents"])
            self._put_object(cid, op.dst, content, batch, released)
            for kind in ("A", "O"):
                for k, v in list(self._kv_iter(
                        self._okey(cid, op.src, kind))):
                    suffix = k.decode().rsplit("/", 1)[-1]
                    self._bset(batch, self._okey(cid, op.dst, kind, suffix), v)
            hdr = self._kv_get(self._okey(cid, op.src, "H"))
            if hdr is not None:
                self._bset(batch, self._okey(cid, op.dst, "H"), hdr)
        elif isinstance(op, os_.OpRename):
            onode_raw = self._kv_get(self._okey(cid, op.src, "N"))
            if onode_raw is None:
                return
            self._bset(batch, self._okey(cid, op.dst, "N"), onode_raw)
            self._brm(batch, self._okey(cid, op.src, "N"))
            for kind in ("A", "O"):
                for k, v in list(self._kv_iter(
                        self._okey(cid, op.src, kind))):
                    suffix = k.decode().rsplit("/", 1)[-1]
                    self._bset(batch, self._okey(cid, op.dst, kind, suffix), v)
                    self._brm(batch, k)
            hdr = self._kv_get(self._okey(cid, op.src, "H"))
            if hdr is not None:
                self._bset(batch, self._okey(cid, op.dst, "H"), hdr)
                self._brm(batch, self._okey(cid, op.src, "H"))
        elif isinstance(op, os_.OpOmapSet):
            for k, v in op.kv.items():
                self._bset(batch, self._okey(cid, op.oid, "O", k.hex()), v)
        elif isinstance(op, os_.OpOmapRmKeys):
            for k in op.keys:
                self._brm(batch, self._okey(cid, op.oid, "O", k.hex()))
        elif isinstance(op, os_.OpOmapClear):
            for k, _ in list(self._kv_iter(
                    self._okey(cid, op.oid, "O"))):
                self._brm(batch, k)
            self._brm(batch, self._okey(cid, op.oid, "H"))
        elif isinstance(op, os_.OpOmapSetHeader):
            self._bset(batch, self._okey(cid, op.oid, "H"), op.data)
        else:
            raise TypeError(f"unknown transaction op {op!r}")

    # -- collections --------------------------------------------------------

    def create_collection(self, cid: spg_t) -> None:
        self.kv.set(self._ckey(cid), b"1")

    def remove_collection(self, cid: spg_t) -> None:
        self.kv.rm(self._ckey(cid))

    def list_collections(self) -> list[spg_t]:
        from ..osd.types import pg_t
        out = []
        for k, _ in self.kv.iterate(b"C/"):
            _, pool, seed, shard = k.decode().split("/")
            out.append(spg_t(pg_t(int(pool), int(seed)), int(shard)))
        return sorted(out)

    def collection_exists(self, cid: spg_t) -> bool:
        return self.kv.get(self._ckey(cid)) is not None

    # -- reads --------------------------------------------------------------

    def read(self, cid, oid, offset=0, length=None) -> np.ndarray:
        with self._lock:
            content = self._content(cid, oid)
        end = len(content) if length is None else min(
            len(content), offset + length)
        return np.frombuffer(content[offset:end], dtype=np.uint8)

    def stat(self, cid, oid) -> int:
        with self._lock:
            onode = self._onode(cid, oid)
        if onode is None:
            raise KeyError(f"no object {oid} in {cid}")
        return onode["size"]

    def exists(self, cid, oid) -> bool:
        with self._lock:
            return self._onode(cid, oid) is not None

    def getattr(self, cid, oid, name) -> bytes:
        with self._lock:
            raw = self._kv_get(self._okey(cid, oid, "A", _esc(name)))
        if raw is None:
            raise KeyError(name)
        return raw

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        out = {}
        prefix = self._okey(cid, oid, "A")
        with self._lock:
            rows = self._kv_iter(prefix)
        for k, v in rows:
            out[self._unesc(k.decode()[len(prefix.decode()):])] = v
        return out

    def omap_get(self, cid, oid) -> dict[bytes, bytes]:
        out = {}
        prefix = self._okey(cid, oid, "O")
        with self._lock:
            rows = self._kv_iter(prefix)
        for k, v in rows:
            out[bytes.fromhex(k.decode()[len(prefix.decode()):])] = v
        return out

    def omap_get_header(self, cid, oid) -> bytes:
        with self._lock:
            return self._kv_get(self._okey(cid, oid, "H")) or b""

    def list_objects(self, cid) -> list[ghobject_t]:
        out = []
        prefix = self._ckey(cid).replace(b"C/", b"N/", 1) + b"/"
        with self._lock:
            rows = list(self.kv.iterate(prefix))
        for k, _ in rows:
            parts = k.decode().split("/")
            name = self._unesc(parts[4])
            key = self._unesc(parts[5])
            h = hobject_t(pool=int(parts[1]), name=name, key=key,
                          snap=int(parts[6]))
            out.append(ghobject_t(h, int(parts[7]), int(parts[8])))
        return sorted(out)

    @staticmethod
    def _unesc(s: str) -> str:
        from .file_store import FileStore
        return FileStore._unesc(s)
