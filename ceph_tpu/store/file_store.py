"""FileStore: durable ObjectStore with a write-ahead journal.

Fills the role of the reference's production stores (src/os/bluestore/
for the architecture: data on the device + metadata in a KV with WAL
atomicity; src/os/filestore/ for the file-per-object layout): every
transaction batch is serialized (the messenger's wire form reused),
crc-protected, appended to the journal and fsync'd BEFORE being applied
— so a crash at any point replays to a consistent state (reference
BlueStore deferred/WAL semantics, BlueStore.h:1504 STATE_DEFERRED_*).

Layout under the store root:
  journal.log              WAL of pending transaction batches
  kv/                      LsmDB: xattrs, omap, object index
  objects/<coll>/<name>    object data files

Object data rides files; everything else rides the KV — the same split
BlueStore makes between the block device and RocksDB.
"""

from __future__ import annotations

import os
import struct
import threading
from pathlib import Path
from typing import Iterable

import numpy as np

from ..common import crc32c as _crc
from ..osd.types import ghobject_t, hobject_t, spg_t
from . import object_store as os_
from .kv import KeyValueDB, WriteBatch, open_kv
from .object_store import ObjectStore, Transaction


def _esc(s: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else f"%{ord(c):02x}"
                   for c in s)


class FileStore(ObjectStore):
    def __init__(self, path: str):
        self.root = Path(path)
        self.journal_path = self.root / "journal.log"
        self.kv: KeyValueDB | None = None
        self._lock = threading.RLock()
        self._journal_f = None
        self._mounted = False

    # -- key scheme ---------------------------------------------------------

    @staticmethod
    def _ckey(cid: spg_t) -> bytes:
        return f"C/{cid.pgid.pool}/{cid.pgid.seed}/{cid.shard}".encode()

    @staticmethod
    def _okey(cid: spg_t, oid: ghobject_t, kind: str,
              extra: str = "") -> bytes:
        h = oid.hobj
        return (f"{kind}/{cid.pgid.pool}/{cid.pgid.seed}/{cid.shard}/"
                f"{_esc(h.name)}/{_esc(h.key)}/{h.snap}/"
                f"{oid.generation}/{oid.shard}/{extra}").encode()

    def _data_path(self, cid: spg_t, oid: ghobject_t) -> Path:
        d = self.root / "objects" / \
            f"{cid.pgid.pool}.{cid.pgid.seed}.{cid.shard}"
        d.mkdir(parents=True, exist_ok=True)
        h = oid.hobj
        return d / f"{_esc(h.name)}.{h.snap}.{oid.generation}.{oid.shard}"

    # -- lifecycle ----------------------------------------------------------

    def mount(self) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        self.kv = open_kv(str(self.root / "kv"))
        self._replay_journal()
        self._journal_f = open(self.journal_path, "ab")
        self._mounted = True

    def umount(self) -> None:
        with self._lock:
            if self._journal_f:
                self._journal_f.close()
                self._journal_f = None
            if self.kv:
                self.kv.compact()
                self.kv.close()
                self.kv = None
            self._mounted = False

    # -- journal ------------------------------------------------------------

    def _journal_append(self, payload: bytes) -> None:
        head = struct.pack("<II", len(payload),
                           _crc.crc32c(payload, 0xFFFFFFFF))
        self._journal_f.write(head + payload)
        self._journal_f.flush()
        os.fsync(self._journal_f.fileno())

    def _replay_journal(self) -> None:
        if not self.journal_path.exists():
            return
        import json
        from ..msg.messages import txn_from_wire
        with open(self.journal_path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                ln, crc = struct.unpack("<II", head)
                body = f.read(ln)
                if len(body) < ln or _crc.crc32c(body, 0xFFFFFFFF) != crc:
                    break  # torn tail
                rec = json.loads(body.decode())
                cid = spg_t.__new__(spg_t)
                from ..osd.types import pg_t
                object.__setattr__(cid, "pgid",
                                   pg_t(rec["cid"][0], rec["cid"][1]))
                object.__setattr__(cid, "shard", rec["cid"][2])
                txn = txn_from_wire(rec["ops"],
                                    bytes.fromhex(rec["blob"]))
                self._apply_txn(cid, txn)
        # applied everything durable: truncate the journal
        open(self.journal_path, "wb").close()

    # -- transactions -------------------------------------------------------

    def queue_transactions(self, cid: spg_t,
                           txns: Iterable[Transaction]) -> None:
        import json
        from ..msg.messages import txn_to_wire
        if not self._mounted:
            raise RuntimeError("store not mounted")
        txns = list(txns)
        callbacks = []
        with self._lock:
            if self.kv.get(self._ckey(cid)) is None:
                raise KeyError(f"no collection {cid}")
            for t in txns:
                ops, blob = txn_to_wire(t)
                rec = json.dumps({
                    "cid": [cid.pgid.pool, cid.pgid.seed, cid.shard],
                    "ops": ops, "blob": blob.hex()}).encode()
                self._journal_append(rec)      # durable intent first
                self._apply_txn(cid, t)        # then apply
                callbacks.extend(t.on_commit)
        for cb in callbacks:
            cb()

    def _apply_txn(self, cid: spg_t, txn: Transaction) -> None:
        for op in txn.ops:
            self._apply(cid, op)

    # -- op application -----------------------------------------------------

    def _size(self, cid, oid) -> int | None:
        raw = self.kv.get(self._okey(cid, oid, "S"))
        return None if raw is None else int(raw)

    def _set_size(self, batch, cid, oid, size: int) -> None:
        batch.set(self._okey(cid, oid, "S"), str(size).encode())

    def _apply(self, cid: spg_t, op) -> None:
        b = WriteBatch()
        if isinstance(op, os_.OpTouch):
            if self._size(cid, op.oid) is None:
                self._data_path(cid, op.oid).write_bytes(b"")
                self._set_size(b, cid, op.oid, 0)
        elif isinstance(op, os_.OpWrite):
            path = self._data_path(cid, op.oid)
            size = self._size(cid, op.oid)
            mode = "r+b" if (size is not None and path.exists()) else "wb"
            with open(path, mode) as f:
                f.seek(op.offset)
                f.write(op.data.tobytes())
                f.flush()
                os.fsync(f.fileno())
            new_size = max(size or 0, op.offset + op.data.size)
            self._set_size(b, cid, op.oid, new_size)
        elif isinstance(op, os_.OpZero):
            path = self._data_path(cid, op.oid)
            size = self._size(cid, op.oid) or 0
            with open(path, "r+b" if path.exists() else "wb") as f:
                f.seek(op.offset)
                f.write(bytes(op.length))
            self._set_size(b, cid, op.oid,
                           max(size, op.offset + op.length))
        elif isinstance(op, os_.OpTruncate):
            path = self._data_path(cid, op.oid)
            if not path.exists():
                path.write_bytes(b"")
            with open(path, "r+b") as f:
                f.truncate(op.size)
            self._set_size(b, cid, op.oid, op.size)
        elif isinstance(op, os_.OpRemove):
            path = self._data_path(cid, op.oid)
            if path.exists():
                path.unlink()
            b.rm(self._okey(cid, op.oid, "S"))
            b.rm(self._okey(cid, op.oid, "H"))
            for k, _ in list(self.kv.iterate(
                    self._okey(cid, op.oid, "A"))):
                b.rm(k)
            for k, _ in list(self.kv.iterate(
                    self._okey(cid, op.oid, "O"))):
                b.rm(k)
        elif isinstance(op, os_.OpSetAttrs):
            if self._size(cid, op.oid) is None:
                self._data_path(cid, op.oid).touch()
                self._set_size(b, cid, op.oid, 0)
            for k, v in op.attrs.items():
                b.set(self._okey(cid, op.oid, "A", _esc(k)), v)
        elif isinstance(op, os_.OpRmAttr):
            b.rm(self._okey(cid, op.oid, "A", _esc(op.name)))
        elif isinstance(op, os_.OpClone):
            src = self._data_path(cid, op.src)
            if src.exists():
                self._data_path(cid, op.dst).write_bytes(
                    src.read_bytes())
                self._set_size(b, cid, op.dst,
                               self._size(cid, op.src) or 0)
                for kind in ("A", "O"):
                    for k, v in list(self.kv.iterate(
                            self._okey(cid, op.src, kind))):
                        suffix = k.decode().rsplit("/", 1)[-1]
                        b.set(self._okey(cid, op.dst, kind, suffix), v)
                hdr = self.kv.get(self._okey(cid, op.src, "H"))
                if hdr is not None:
                    b.set(self._okey(cid, op.dst, "H"), hdr)
        elif isinstance(op, os_.OpRename):
            src = self._data_path(cid, op.src)
            if src.exists():
                os.replace(src, self._data_path(cid, op.dst))
                self._set_size(b, cid, op.dst,
                               self._size(cid, op.src) or 0)
                b.rm(self._okey(cid, op.src, "S"))
                # attrs and omap travel with the object (generations
                # rely on rename preserving the hinfo xattr)
                for kind in ("A", "O"):
                    for k, v in list(self.kv.iterate(
                            self._okey(cid, op.src, kind))):
                        suffix = k.decode().rsplit("/", 1)[-1]
                        b.set(self._okey(cid, op.dst, kind, suffix), v)
                        b.rm(k)
                hdr = self.kv.get(self._okey(cid, op.src, "H"))
                if hdr is not None:
                    b.set(self._okey(cid, op.dst, "H"), hdr)
                    b.rm(self._okey(cid, op.src, "H"))
        elif isinstance(op, os_.OpOmapSet):
            for k, v in op.kv.items():
                b.set(self._okey(cid, op.oid, "O", k.hex()), v)
        elif isinstance(op, os_.OpOmapRmKeys):
            for k in op.keys:
                b.rm(self._okey(cid, op.oid, "O", k.hex()))
        elif isinstance(op, os_.OpOmapClear):
            for k, _ in list(self.kv.iterate(
                    self._okey(cid, op.oid, "O"))):
                b.rm(k)
            b.rm(self._okey(cid, op.oid, "H"))
        elif isinstance(op, os_.OpOmapSetHeader):
            b.set(self._okey(cid, op.oid, "H"), op.data)
        else:
            raise TypeError(f"unknown transaction op {op!r}")
        if b.ops:
            self.kv.submit(b, sync=False)  # journal already made it durable

    # -- collections --------------------------------------------------------

    def create_collection(self, cid: spg_t) -> None:
        self.kv.set(self._ckey(cid), b"1")

    def remove_collection(self, cid: spg_t) -> None:
        self.kv.rm(self._ckey(cid))

    def list_collections(self) -> list[spg_t]:
        from ..osd.types import pg_t
        out = []
        for k, _ in self.kv.iterate(b"C/"):
            _, pool, seed, shard = k.decode().split("/")
            out.append(spg_t(pg_t(int(pool), int(seed)), int(shard)))
        return sorted(out)

    def collection_exists(self, cid: spg_t) -> bool:
        return self.kv.get(self._ckey(cid)) is not None

    # -- reads --------------------------------------------------------------

    def read(self, cid, oid, offset=0, length=None) -> np.ndarray:
        size = self._size(cid, oid)
        if size is None:
            raise KeyError(f"no object {oid} in {cid}")
        path = self._data_path(cid, oid)
        data = path.read_bytes() if path.exists() else b""
        if len(data) < size:
            data = data + bytes(size - len(data))
        end = size if length is None else min(size, offset + length)
        return np.frombuffer(data[offset:end], dtype=np.uint8)

    def stat(self, cid, oid) -> int:
        size = self._size(cid, oid)
        if size is None:
            raise KeyError(f"no object {oid} in {cid}")
        return size

    def exists(self, cid, oid) -> bool:
        return self._size(cid, oid) is not None

    def getattr(self, cid, oid, name) -> bytes:
        raw = self.kv.get(self._okey(cid, oid, "A", _esc(name)))
        if raw is None:
            raise KeyError(name)
        return raw

    def getattrs(self, cid, oid) -> dict[str, bytes]:
        out = {}
        prefix = self._okey(cid, oid, "A")
        for k, v in self.kv.iterate(prefix):
            out[self._unesc(k.decode()[len(prefix.decode()):])] = v
        return out

    def omap_get(self, cid, oid) -> dict[bytes, bytes]:
        out = {}
        prefix = self._okey(cid, oid, "O")
        for k, v in self.kv.iterate(prefix):
            out[bytes.fromhex(k.decode()[len(prefix.decode()):])] = v
        return out

    def omap_get_header(self, cid, oid) -> bytes:
        return self.kv.get(self._okey(cid, oid, "H")) or b""

    def list_objects(self, cid) -> list[ghobject_t]:
        out = []
        prefix = self._ckey(cid).replace(b"C/", b"S/", 1) + b"/"
        for k, _ in self.kv.iterate(prefix):
            parts = k.decode().split("/")
            # S/pool/seed/shard/name/key/snap/gen/oshard/
            name = self._unesc(parts[4])
            key = self._unesc(parts[5])
            h = hobject_t(pool=int(parts[1]), name=name, key=key,
                          snap=int(parts[6]))
            out.append(ghobject_t(h, int(parts[7]), int(parts[8])))
        return sorted(out)

    @staticmethod
    def _unesc(s: str) -> str:
        out = []
        i = 0
        while i < len(s):
            if s[i] == "%":
                out.append(chr(int(s[i + 1:i + 3], 16)))
                i += 3
            else:
                out.append(s[i])
                i += 1
        return "".join(out)
