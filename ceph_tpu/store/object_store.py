"""ObjectStore contract + Transaction.

Re-expresses the reference's `ObjectStore`/`ObjectStore::Transaction`
(src/os/ObjectStore.h, src/os/Transaction.h): an ordered batch of
mutations applied atomically to one collection-set, with commit
callbacks.  The OSD's backends build Transactions and
`queue_transactions` them; the store decides durability.

Ops are a small closed set (the reference's Transaction::Op enum),
carried as dataclass records so stores replay them; EC restricts itself
to the rollbackable subset (append/remove-keeping-gen/setattr with
prior-value retention — reference
doc/dev/osd_internals/erasure_coding/ecbackend.rst:9-27).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Callable, Iterable

import numpy as np

from ..osd.types import ghobject_t, spg_t


# -- transaction ops ---------------------------------------------------------

@dataclass
class OpTouch:
    oid: ghobject_t


@dataclass
class OpWrite:
    oid: ghobject_t
    offset: int
    data: np.ndarray          # uint8

    def __post_init__(self):
        self.data = np.asarray(self.data, dtype=np.uint8).ravel()


@dataclass
class OpZero:
    oid: ghobject_t
    offset: int
    length: int


@dataclass
class OpTruncate:
    oid: ghobject_t
    size: int


@dataclass
class OpRemove:
    oid: ghobject_t


@dataclass
class OpSetAttrs:
    oid: ghobject_t
    attrs: dict[str, bytes]


@dataclass
class OpRmAttr:
    oid: ghobject_t
    name: str


@dataclass
class OpClone:
    src: ghobject_t
    dst: ghobject_t


@dataclass
class OpRename:
    src: ghobject_t
    dst: ghobject_t


@dataclass
class OpOmapSet:
    oid: ghobject_t
    kv: dict[bytes, bytes]


@dataclass
class OpOmapRmKeys:
    oid: ghobject_t
    keys: list[bytes]


@dataclass
class OpOmapClear:
    oid: ghobject_t


@dataclass
class OpOmapSetHeader:
    oid: ghobject_t
    data: bytes


class Transaction:
    """Ordered op batch + commit callbacks (reference Transaction.h)."""

    def __init__(self) -> None:
        self.ops: list = []
        self.on_commit: list[Callable[[], None]] = []

    def empty(self) -> bool:
        return not self.ops

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)
        self.on_commit.extend(other.on_commit)

    # builder helpers
    def touch(self, oid):            self.ops.append(OpTouch(oid))
    def write(self, oid, off, data): self.ops.append(OpWrite(oid, off, data))
    def zero(self, oid, off, ln):    self.ops.append(OpZero(oid, off, ln))
    def truncate(self, oid, size):   self.ops.append(OpTruncate(oid, size))
    def remove(self, oid):           self.ops.append(OpRemove(oid))
    def setattrs(self, oid, attrs):  self.ops.append(OpSetAttrs(oid, dict(attrs)))
    def setattr(self, oid, k, v):    self.ops.append(OpSetAttrs(oid, {k: bytes(v)}))
    def rmattr(self, oid, k):        self.ops.append(OpRmAttr(oid, k))
    def clone(self, src, dst):       self.ops.append(OpClone(src, dst))
    def rename(self, src, dst):      self.ops.append(OpRename(src, dst))
    def omap_setkeys(self, oid, kv): self.ops.append(OpOmapSet(oid, dict(kv)))
    def omap_rmkeys(self, oid, ks):  self.ops.append(OpOmapRmKeys(oid, list(ks)))
    def omap_clear(self, oid):       self.ops.append(OpOmapClear(oid))
    def omap_setheader(self, oid, d): self.ops.append(OpOmapSetHeader(oid, bytes(d)))

    def register_on_commit(self, cb: Callable[[], None]) -> None:
        self.on_commit.append(cb)


# -- store contract ----------------------------------------------------------

class ObjectStore(abc.ABC):
    """Reference src/os/ObjectStore.h: collections of objects with data,
    xattrs and omap; transactional writes; enumerable for scrub."""

    @abc.abstractmethod
    def mount(self) -> None: ...

    @abc.abstractmethod
    def umount(self) -> None: ...

    @abc.abstractmethod
    def create_collection(self, cid: spg_t) -> None: ...

    @abc.abstractmethod
    def remove_collection(self, cid: spg_t) -> None: ...

    @abc.abstractmethod
    def list_collections(self) -> list[spg_t]: ...

    @abc.abstractmethod
    def collection_exists(self, cid: spg_t) -> bool: ...

    @abc.abstractmethod
    def queue_transactions(self, cid: spg_t,
                           txns: Iterable[Transaction]) -> None:
        """Apply transactions atomically-per-txn and fire on_commit.
        (reference ObjectStore::queue_transactions, the call ECBackend
        makes at src/osd/ECBackend.cc:983)"""

    # -- reads --------------------------------------------------------------

    @abc.abstractmethod
    def read(self, cid: spg_t, oid: ghobject_t, offset: int = 0,
             length: int | None = None) -> np.ndarray: ...

    @abc.abstractmethod
    def stat(self, cid: spg_t, oid: ghobject_t) -> int:
        """Object size; raises KeyError if absent."""

    @abc.abstractmethod
    def exists(self, cid: spg_t, oid: ghobject_t) -> bool: ...

    @abc.abstractmethod
    def getattr(self, cid: spg_t, oid: ghobject_t, name: str) -> bytes: ...

    @abc.abstractmethod
    def getattrs(self, cid: spg_t, oid: ghobject_t) -> dict[str, bytes]: ...

    @abc.abstractmethod
    def omap_get(self, cid: spg_t, oid: ghobject_t) -> dict[bytes, bytes]: ...

    def omap_get_header(self, cid: spg_t, oid: ghobject_t) -> bytes:
        """Omap header blob (reference ObjectStore omap_get_header);
        empty when never set."""
        return b""

    @abc.abstractmethod
    def list_objects(self, cid: spg_t) -> list[ghobject_t]: ...
