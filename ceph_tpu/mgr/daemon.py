"""MgrDaemon: map subscription + module host.

Re-expresses reference src/mgr/Mgr.cc + PyModuleRegistry: the daemon
keeps a live OSDMap from the mon and runs each enabled module's
serve() on its own thread; modules reach the cluster through the
MgrModule API (get_osdmap / mon_command / set_health), the analog of
the reference's ActivePyModules surface.
"""

from __future__ import annotations

import threading
import time

from ..msg import Messenger
from ..msg import messages as M
from ..osd.osd_map import OSDMap, apply_inc_chain


class MgrModule:
    """Base class for mgr modules (reference MgrModule in
    pybind/mgr/mgr_module.py)."""

    name = "module"
    run_interval = 1.0

    def __init__(self, mgr: "MgrDaemon"):
        self.mgr = mgr

    def tick(self) -> None:
        """Called every run_interval while the mgr is active."""

    def shutdown(self) -> None:
        """Optional teardown (servers, files) at mgr shutdown."""

    # convenience passthroughs
    def get_osdmap(self) -> OSDMap:
        return self.mgr.osdmap

    def mon_command(self, cmd: dict) -> tuple[int, dict]:
        return self.mgr.mon_command(cmd)


class MgrDaemon:
    def __init__(self, mon_addr, modules: list[type] | None = None,
                 auth=None, secure: bool = False, name: str = "x"):
        from ..msg.addrs import normalize_mon_addrs
        self.name = name
        self.mon_addrs = normalize_mon_addrs(mon_addr)
        self._mon_idx = 0
        self.messenger = Messenger("mgr", auth=auth, secure=secure)
        self.messenger.add_dispatcher(self._dispatch)
        self.mon_conn = self.messenger.connect(self.mon_addrs[0])
        self.osdmap = OSDMap()
        self.map_event = threading.Event()
        self._lock = threading.Lock()
        self._tid = 0
        self._waiters: dict[int, dict] = {}
        self.health: dict[str, dict] = {}   # module -> health report
        self._stop = threading.Event()
        from .modules import DEFAULT_MODULES
        self.modules = [cls(self) for cls in
                        (modules if modules is not None
                         else DEFAULT_MODULES)]
        self._threads: list[threading.Thread] = []

    def start(self, timeout: float = 10.0) -> "MgrDaemon":
        deadline = time.time() + timeout
        while self.osdmap.epoch == 0 and time.time() < deadline:
            self.mon_conn.send_message(M.MMonGetMap())
            if not self.map_event.wait(1.0):
                self._rotate_mon()
            self.map_event.clear()
        try:
            # join the replicated mgrmap (reference MgrMonitor beacon:
            # first mgr becomes active, later ones standby)
            self.mon_command({"prefix": "mgr boot", "name": self.name})
        except Exception:  # noqa: BLE001 - registration is best-effort
            pass
        for mod in self.modules:
            t = threading.Thread(target=self._run_module, args=(mod,),
                                 daemon=True, name=f"mgr.{mod.name}")
            t.start()
            self._threads.append(t)
        return self

    def shutdown(self) -> None:
        self._stop.set()
        for mod in self.modules:
            try:
                mod.shutdown()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self.messenger.shutdown()

    def _rotate_mon(self) -> None:
        if len(self.mon_addrs) == 1:
            return
        self._mon_idx = (self._mon_idx + 1) % len(self.mon_addrs)
        self.mon_conn = self.messenger.connect(
            self.mon_addrs[self._mon_idx])

    def _run_module(self, mod: MgrModule) -> None:
        while not self._stop.wait(mod.run_interval):
            try:
                mod.tick()
            except Exception as e:  # noqa: BLE001 - module crash is
                # the module's problem, not the mgr's (reference
                # PyModule health error surface)
                self.health[mod.name] = {
                    "status": "HEALTH_ERR",
                    "detail": [f"module {mod.name} failed: {e!r}"]}

    def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, M.MMonMap):
            newmap = OSDMap.from_json(msg.map_json)
            if newmap.epoch >= self.osdmap.epoch:
                self.osdmap = newmap
            self.map_event.set()
        elif isinstance(msg, M.MOSDMapInc):
            # incremental publish / keepalive (same contract as the
            # OSD/objecter appliers; gap -> full re-request)
            m = apply_inc_chain(self.osdmap, msg.incs)
            if m is None or (not msg.incs and
                             msg.epoch > self.osdmap.epoch):
                try:
                    self.mon_conn.send_message(M.MMonGetMap())
                except Exception:  # noqa: BLE001 - mon electing
                    pass
                return
            self.osdmap = m
            self.map_event.set()
        elif isinstance(msg, M.MMonCommandAck):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()

    def mon_command(self, cmd: dict, timeout: float = 10.0
                    ) -> tuple[int, dict]:
        with self._lock:
            self._tid += 1
            tid = self._tid
            w = {"event": threading.Event(), "reply": None}
            self._waiters[tid] = w
        self.mon_conn.send_message(M.MMonCommand(cmd, tid))
        if not w["event"].wait(timeout):
            self._rotate_mon()
            raise TimeoutError(f"mon command {cmd.get('prefix')}")
        return w["reply"].result, w["reply"].out

    # -- health model (reference Mgr health aggregation) --------------------

    def set_health(self, module: str, status: str,
                   detail: list[str]) -> None:
        if status == "HEALTH_OK":
            self.health.pop(module, None)
        else:
            self.health[module] = {"status": status, "detail": detail}

    def health_summary(self) -> dict:
        worst = "HEALTH_OK"
        for rep in self.health.values():
            if rep["status"] == "HEALTH_ERR":
                worst = "HEALTH_ERR"
            elif worst == "HEALTH_OK":
                worst = rep["status"]
        return {"status": worst, "checks": dict(self.health)}
