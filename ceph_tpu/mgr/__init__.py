"""Manager (mgr): the module host for cluster-level services.

Re-expresses the reference's ceph-mgr (src/mgr/ + src/pybind/mgr/):
a daemon that subscribes to cluster maps and hosts pluggable python
modules behind a small MgrModule API.  Built-in modules:

- health: cluster health model (HEALTH_OK/WARN/ERR from down OSDs,
  degraded PGs, missing quorum) — the `ceph status` health role.
- balancer: evens the PG-per-OSD distribution by proposing pg_temp
  remaps (the upmap balancer role, reference pybind/mgr/balancer).
- pg_autoscaler: recommends pg_num per pool from utilization
  (advisory — pools here don't split PGs; reference
  pybind/mgr/pg_autoscaler biases the same math).
- prometheus: the metrics exporter (tools/metrics_exporter wraps it
  for standalone use).
"""

from .daemon import MgrDaemon, MgrModule

__all__ = ["MgrDaemon", "MgrModule"]
