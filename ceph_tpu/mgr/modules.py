"""Built-in mgr modules: health, balancer, pg_autoscaler, telemetry,
devicehealth, dashboard.

Reference analogs: the mgr health aggregation (src/mgr/DaemonHealth*),
pybind/mgr/balancer (upmap mode re-expressed over pg_temp, the map's
explicit acting-set override), and pybind/mgr/pg_autoscaler (advisory
by default; pools that opt in with pg_autoscale_mode=on get real
pg_num increases issued through the mon, which the OSDs execute as
live PG splits).
"""

from __future__ import annotations

import threading

from ..crush.map import CRUSH_ITEM_NONE
from ..osd.types import pg_t
from .daemon import MgrModule


class HealthModule(MgrModule):
    """Cluster health from the map: down/out OSDs, PGs below size."""

    name = "health"
    run_interval = 0.5

    def tick(self) -> None:
        m = self.get_osdmap()
        warns: list[str] = []
        errs: list[str] = []
        down = [o.id for o in m.osds.values() if not o.up]
        if down:
            warns.append(f"{len(down)} osds down: {down}")
        degraded = 0
        unavailable = 0
        for pool in m.pools.values():
            for seed in range(pool.pg_num):
                try:
                    _, acting, _, _ = m.pg_to_up_acting_osds(
                        pg_t(pool.id, seed))
                except Exception:  # noqa: BLE001
                    continue
                live = sum(1 for o in acting
                           if o != CRUSH_ITEM_NONE and m.is_up(o))
                if live < pool.min_size:
                    unavailable += 1
                elif live < pool.size:
                    degraded += 1
        if degraded:
            warns.append(f"{degraded} pgs degraded")
        if unavailable:
            errs.append(f"{unavailable} pgs below min_size")
        status = "HEALTH_ERR" if errs else (
            "HEALTH_WARN" if warns else "HEALTH_OK")
        self.mgr.set_health(self.name, status, errs + warns)


class BalancerModule(MgrModule):
    """Even the PG->OSD distribution with pg_upmap_items (the upmap
    balancer; reference pybind/mgr/balancer upmap mode over
    OSDMap::calc_pg_upmaps).  Greedy: substitute one device at a time
    on the most-loaded OSD's PGs toward the least-loaded OSD until the
    spread is within threshold.  Upmap items override the RAW crush
    result per PG, so they compose with CRUSH and survive remaps of
    unrelated devices — unlike the pg_temp acting-set override, which
    stays the peering/backfill lever."""

    name = "balancer"
    run_interval = 2.0
    max_moves_per_tick = 4
    threshold = 1          # max-min PG count gap considered balanced

    def __init__(self, mgr):
        super().__init__(mgr)
        self.active = True
        self.moves = 0

    def compute_moves(self) -> list[tuple[pg_t, list[tuple[int, int]]]]:
        """-> [(pgid, upmap pairs for that pg)] — the calc_pg_upmaps
        role."""
        m = self.get_osdmap()
        up_osds = [o.id for o in m.osds.values() if o.up and o.in_]
        if len(up_osds) < 2:
            return []
        load: dict[int, int] = {o: 0 for o in up_osds}
        # positional raw+upmap lists (NOT the compacted up set: zip
        # alignment with the raw crush result must hold even when a
        # raw-set OSD is down)
        placement: dict[pg_t, list[int]] = {}
        for pool in m.pools.values():
            for seed in range(pool.pg_num):
                pgid = pg_t(pool.id, seed)
                try:
                    cur = m.pg_to_raw_upmap_osds(pgid)
                except Exception:  # noqa: BLE001
                    continue
                placement[pgid] = list(cur)
                for o in cur:
                    if o in load:
                        load[o] += 1
        touched: set[pg_t] = set()
        for _ in range(self.max_moves_per_tick):
            hot = max(load, key=load.get)
            cold = min(load, key=load.get)
            if load[hot] - load[cold] <= self.threshold:
                break
            # one PG mapped onto `hot` whose up set lacks `cold`
            for pgid, up in placement.items():
                if hot in up and cold not in up:
                    placement[pgid] = [cold if o == hot else o
                                       for o in up]
                    touched.add(pgid)
                    load[hot] -= 1
                    load[cold] += 1
                    break
            else:
                break
        # emit each touched PG's items as the POSITIONAL diff of the
        # raw crush result vs the desired placement — a simultaneous
        # substitution map with no chains (how calc_pg_upmaps emits)
        out = []
        for pgid in touched:
            raw = m.pg_to_raw_osds(pgid)
            pairs = sorted((o, d) for o, d in
                           zip(raw, placement[pgid]) if o != d)
            out.append((pgid, pairs))
        return out

    def tick(self) -> None:
        if not self.active:
            return
        for pgid, pairs in self.compute_moves():
            r, _ = self.mon_command({
                "prefix": "osd pg-upmap-items",
                "pgid": [pgid.pool, pgid.seed],
                "pairs": [list(p) for p in pairs]})
            if r == 0:
                self.moves += 1


class PgAutoscalerModule(MgrModule):
    """Recommend — and, for opted-in pools, APPLY — pg_num per pool
    (reference pybind/mgr/pg_autoscaler): target ~quarter of the
    reference's 100 PGs per OSD, power of two.

    Pools default to advisory mode (a health warning when far off).
    A pool with pg_autoscale_mode=on (`ceph osd pool set <pool>
    pg_autoscale_mode on`) gets real `osd pool set pg_num` commands
    in BOTH directions: the mon commits the change through Paxos and
    the OSDs split or merge the PGs live.  Stepped at most `max_step`x
    per tick so one tick never floods the cluster with every split or
    merge at once; a decrease the mon refuses (split still settling —
    the interleave guard) simply retries on a later tick."""

    name = "pg_autoscaler"
    run_interval = 2.0
    target_pgs_per_osd = 32
    max_step = 4           # per-tick resize factor cap (power of two)

    def recommendations(self) -> dict[str, int]:
        m = self.get_osdmap()
        n_osds = sum(1 for o in m.osds.values() if o.up and o.in_)
        if not n_osds or not m.pools:
            return {}
        budget = n_osds * self.target_pgs_per_osd
        per_pool = max(1, budget // max(1, len(m.pools)))
        rec = 1 << (per_pool.bit_length() - 1)   # floor power of two
        return {p.name: rec for p in m.pools.values()}

    def tick(self) -> None:
        m = self.get_osdmap()
        recs = self.recommendations()
        warns = []
        for p in m.pools.values():
            want = recs.get(p.name, p.pg_num)
            mode = getattr(p, "pg_autoscale_mode", "warn")
            if mode == "on" and want != p.pg_num and p.pg_num and \
                    p.pg_num & (p.pg_num - 1) == 0:
                if want > p.pg_num:
                    target = min(want, p.pg_num * self.max_step)
                elif want * 4 <= p.pg_num:
                    # scale DOWN too (PG merge): capped step, and only
                    # past a 4x hysteresis band — a transiently-down
                    # OSD shrinking the recommendation must not
                    # trigger merge/split thrash.  The mon rejects
                    # with EBUSY while a split is settling (interleave
                    # guard) — retry next tick.
                    target = max(want, max(1,
                                           p.pg_num // self.max_step))
                else:
                    target = p.pg_num   # inside the band: leave it
                if target != p.pg_num:
                    r, _out = self.mon_command({
                        "prefix": "osd pool set", "pool": p.name,
                        "var": "pg_num", "val": str(target)})
                    if r == 0:
                        continue   # acted; re-evaluate next tick
            if want >= 4 * p.pg_num or p.pg_num >= 4 * want:
                warns.append(
                    f"pool {p.name!r} pg_num {p.pg_num} far from "
                    f"recommended {want}")
        self.mgr.set_health(
            self.name,
            "HEALTH_WARN" if warns else "HEALTH_OK", warns)


class TelemetryModule(MgrModule):
    """Periodic anonymized cluster report (reference pybind/mgr/
    telemetry — there it phones home; here the report is exposed on
    the module and, when a report path is set, written as JSON for an
    operator to forward)."""

    name = "telemetry"
    run_interval = 5.0
    report_path: str | None = None       # set by operator/tests

    def __init__(self, mgr):
        super().__init__(mgr)
        self.last_report: dict | None = None

    def compile_report(self) -> dict:
        import time as _time
        m = self.get_osdmap()
        pools = list(m.pools.values())
        return {
            "report_timestamp": _time.time(),
            "osdmap_epoch": m.epoch,
            "osds": {"total": len(m.osds),
                     "up": sum(1 for o in m.osds.values() if o.up),
                     "in": sum(1 for o in m.osds.values() if o.in_)},
            "pools": {"total": len(pools),
                      "replicated": sum(1 for p in pools
                                        if not p.is_erasure()),
                      "erasure": sum(1 for p in pools
                                     if p.is_erasure()),
                      "pg_total": sum(p.pg_num for p in pools)},
            "ec_profiles": sorted(
                {p.erasure_code_profile for p in pools
                 if p.is_erasure()}),
            "health": self.mgr.health_summary().get("status"),
        }

    def tick(self) -> None:
        self.last_report = self.compile_report()
        if self.report_path:
            import json as _json
            import os as _os
            tmp = self.report_path + ".tmp"
            with open(tmp, "w") as f:      # atomic swap: a reader
                _json.dump(self.last_report, f, indent=2)   # never
            _os.replace(tmp, self.report_path)   # sees partial JSON


class DeviceHealthModule(MgrModule):
    """Failing-device early warning (reference pybind/mgr/devicehealth,
    reduced: no SMART source here, so the signal is FLAPPING — an OSD
    that bounces down repeatedly inside the window is predicted
    unhealthy and surfaced before it dies for good)."""

    name = "devicehealth"
    run_interval = 1.0
    window_s = 600.0
    flap_threshold = 3

    def __init__(self, mgr):
        super().__init__(mgr)
        self._was_up: dict[int, bool] = {}
        self._downs: dict[int, list[float]] = {}

    def tick(self) -> None:
        import time as _time
        m = self.get_osdmap()
        now = _time.time()
        warns = []
        for o in m.osds.values():
            prev = self._was_up.get(o.id)
            if prev is True and not o.up:
                self._downs.setdefault(o.id, []).append(now)
            self._was_up[o.id] = o.up
        for osd_id, downs in self._downs.items():
            recent = [t for t in downs if now - t < self.window_s]
            self._downs[osd_id] = recent
            if len(recent) >= self.flap_threshold:
                warns.append(
                    f"osd.{osd_id} flapped {len(recent)}x in "
                    f"{int(self.window_s)}s: possible failing device")
        self.mgr.set_health(
            self.name, "HEALTH_WARN" if warns else "HEALTH_OK", warns)


class DashboardModule(MgrModule):
    """Read-only cluster dashboard (reference pybind/mgr/dashboard,
    reduced to the observability core): an HTTP endpoint serving an
    HTML summary plus /api/health, /api/osds, /api/pools JSON."""

    name = "dashboard"
    run_interval = 3600.0                # serving is thread-driven
    port = 0                             # 0 = ephemeral

    def __init__(self, mgr):
        super().__init__(mgr)
        import http.server
        import json as _json
        import threading as _threading
        module = self

        class _H(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _json(self, obj):
                body = _json.dumps(obj, indent=2).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                m = module.get_osdmap()
                if self.path == "/api/health":
                    self._json(module.mgr.health_summary())
                elif self.path == "/api/osds":
                    self._json([{"id": o.id, "up": o.up, "in": o.in_,
                                 "addr": list(o.addr or ())}
                                for o in m.osds.values()])
                elif self.path == "/api/pools":
                    self._json([{"name": p.name, "id": p.id,
                                 "type": ("erasure" if p.is_erasure()
                                          else "replicated"),
                                 "size": p.size, "pg_num": p.pg_num}
                                for p in m.pools.values()])
                elif self.path == "/":
                    from html import escape as _esc
                    h = module.mgr.health_summary()
                    up = sum(1 for o in m.osds.values() if o.up)
                    rows = "".join(
                        f"<tr><td>{_esc(p.name)}</td><td>{p.size}</td>"
                        f"<td>{p.pg_num}</td></tr>"
                        for p in m.pools.values())
                    body = (
                        "<html><head><title>ceph-tpu</title></head>"
                        "<body><h1>ceph-tpu dashboard</h1>"
                        f"<p>health: "
                        f"<b>{_esc(str(h.get('status')))}</b></p>"
                        f"<p>epoch {m.epoch}; {up}/{len(m.osds)} "
                        "osds up</p>"
                        "<table border=1><tr><th>pool</th><th>size"
                        "</th><th>pg_num</th></tr>"
                        f"{rows}</table></body></html>").encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()

        import http.server as _hs
        self.httpd = _hs.ThreadingHTTPServer(("127.0.0.1", self.port),
                                             _H)
        self.addr = self.httpd.server_address
        _threading.Thread(target=self.httpd.serve_forever,
                          daemon=True,
                          name="mgr-dashboard").start()

    def tick(self) -> None:
        pass

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class ProgressModule(MgrModule):
    """Completion fractions for long-running cluster motion (reference
    pybind/mgr/progress): recovery and backfill events derived from
    `pg stat` each tick, plus externally-noted events (rgw reshard).

    Event model: while a count (degraded PGs, misplaced objects) is
    nonzero, the event's BASELINE is the max count seen during the
    episode and progress = 1 - cur/baseline — monotone even when the
    count wobbles upward mid-recovery (the baseline rises with it).
    When the count returns to zero the event is pushed at 1.0, where
    the mon's linger window keeps it visible to pollers before it
    retires.  Events live in the LEADER's transient store (`progress
    update` mon command), so `ceph_cli progress` and the `status`
    one-liners answer without a mgr round-trip."""

    name = "progress"
    run_interval = 0.5

    # externally-noted events (class-level so sibling modules can note
    # without holding a ProgressModule reference)
    _ext_lock = threading.Lock()
    _external: dict[str, dict] = {}

    @classmethod
    def note_event(cls, eid: str, message: str,
                   progress: float) -> None:
        with cls._ext_lock:
            cls._external[eid] = {"message": message,
                                  "progress": progress}

    def __init__(self, mgr):
        super().__init__(mgr)
        self._baseline: dict[str, int] = {}     # episode max count
        self._started: dict[str, float] = {}    # episode start ts
        self.events: dict[str, float] = {}      # last pushed fraction

    def _push(self, eid: str, message: str, frac: float) -> None:
        cmd = {"prefix": "progress update", "id": eid,
               "message": message, "progress": frac}
        if eid in self._started:
            cmd["started_at"] = self._started[eid]
        r, _out = self.mon_command(cmd)
        if r == 0:
            self.events[eid] = frac

    def _track(self, eid: str, what: str, cur: int) -> None:
        import time as _time
        if cur <= 0:
            if eid in self._baseline:
                # episode over: publish the 1.0, then forget the
                # episode so the next one starts a fresh baseline
                self._push(eid, f"{what} (done)", 1.0)
                del self._baseline[eid]
                self._started.pop(eid, None)
                self.events.pop(eid, None)
            return
        base = max(self._baseline.get(eid, 0), cur)
        self._baseline[eid] = base
        self._started.setdefault(eid, _time.time())
        frac = 1.0 - cur / base if base else 0.0
        # monotone within the episode: a shrinking baseline ratio must
        # never walk a published fraction backwards
        frac = max(frac, self.events.get(eid, 0.0))
        self._push(eid, f"{what} ({cur} remaining)", min(frac, 0.999))

    def tick(self) -> None:
        r, out = self.mon_command({"prefix": "pg stat"})
        if r == 0:
            self._track("recovery", "Recovery: degraded PGs",
                        int(out.get("degraded_pgs", 0)))
            self._track("backfill", "Backfill: misplaced objects",
                        int(out.get("misplaced_objects", 0)))
        with self._ext_lock:
            ext = dict(self._external)
            self._external.clear()
        for eid, ev in ext.items():
            self._push(eid, ev["message"],
                       max(0.0, min(1.0, float(ev["progress"]))))


class RgwReshardModule(MgrModule):
    """Dynamic bucket-index resharding driver (reference
    pybind/mgr's rgw support + RGWReshard's background processor).

    RGW stores register themselves at gateway construction (class
    registry — the in-process clusters this build runs host mgr and
    radosgw in one interpreter); each tick sweeps every attached
    store: resume reshards interrupted by a daemon kill, autoscale
    buckets whose per-shard entry count exceeds
    rgw_max_objs_per_shard.  Sweeps are cheap when nothing is over
    threshold (one dir_count per shard per bucket)."""

    name = "rgw_reshard"
    run_interval = 5.0

    _stores: list = []          # class-level: shared across daemons
    _reg_lock = threading.Lock()

    @classmethod
    def attach(cls, store) -> None:
        with cls._reg_lock:
            if store not in cls._stores:
                cls._stores.append(store)

    @classmethod
    def detach(cls, store) -> None:
        with cls._reg_lock:
            if store in cls._stores:
                cls._stores.remove(store)

    def tick(self) -> None:
        with self._reg_lock:
            stores = list(self._stores)
        msgs: list[str] = []
        for store in stores:
            try:
                stats = store.reshard_sweep()
            except Exception as e:  # noqa: BLE001 - degraded cluster
                msgs.append(f"reshard sweep failed: {e}")
                continue
            n = stats.get("resumed", 0) + stats.get("started", 0)
            if n:
                msgs.append(f"resharded {n} bucket(s)")
                # surface the reshard in `progress` too (one-shot,
                # already complete by the time the sweep returns)
                ProgressModule.note_event(
                    "rgw-reshard", f"Reshard: {n} bucket(s)", 1.0)
        self.mgr.set_health(self.name,
                            "HEALTH_WARN" if any(
                                "failed" in m for m in msgs)
                            else "HEALTH_OK", msgs)


DEFAULT_MODULES = [HealthModule, BalancerModule, PgAutoscalerModule,
                   TelemetryModule, DeviceHealthModule,
                   ProgressModule, RgwReshardModule]

