"""CephFS client: libcephfs-role POSIX-ish surface.

Re-expresses reference src/client/Client.cc + libcephfs.h at the
surface a filesystem consumer needs: mount, open/create, pread/pwrite
with block striping straight to the data pool (the MDS never sees file
bytes — reference file I/O goes client->OSD under caps), mkdir,
readdir, rename, unlink, rmdir, stat, truncate.

Capabilities (reference client cap handling, reduced): open() asks the
MDS for caps.  A sole opener gets "rwc" — the "c" cap is the right to
cache stat results (dentry-lease role) and defer the size/mtime
writeback to close().  When another client opens the same inode the
MDS revokes "c": this client flushes dirty attrs immediately, drops
its stat cache, and acks — after which every write is written through
(attr flush per write) so contenders observe each other.
"""

from __future__ import annotations

import os
import threading
import time

from ..msg import Messenger
from ..msg import messages as M
from .mds import data_oid

LEASE_TTL = 5.0      # stat-cache lifetime under the "c" cap


def _norm(path: str) -> str:
    """One normalization for every _stat_cache key: insert and
    invalidate must never disagree on the spelling of a path."""
    return "/" + "/".join(p for p in path.split("/") if p)


class FSError(Exception):
    def __init__(self, err: int, msg: str = ""):
        super().__init__(f"[errno {err}] {msg}")
        self.errno = err


class CephFS:
    def __init__(self, mon_addr, mds_addr, auth=None,
                 secure: bool = False, name: str = "fsclient"):
        from ..rados import RadosClient
        self.client_id = f"{name}.{os.urandom(6).hex()}"
        self.messenger = Messenger(name, auth=auth, secure=secure)
        self.messenger.add_dispatcher(self._dispatch)
        self.mds_conn = self.messenger.connect(tuple(mds_addr))
        self._mds_conns: dict[tuple, object] = {}   # other ranks
        self._route_cache: dict[str, tuple] = {}    # path -> owner addr
        self._lock = threading.Lock()
        self._tid = 0
        self._waiters: dict[int, dict] = {}
        self._caps: dict[int, str] = {}              # ino -> caps held
        self._cap_seqs: dict[int, int] = {}          # ino -> last seq
        self._attr_tick = 0      # per-client attr-update order stamp
        self._snap_epoch = -1    # last applied snapc epoch
        self._early_snapc = None  # broadcast that beat mount()
        self.data = None
        self._files: dict[int, list] = {}            # ino -> open Files
        self._stat_cache: dict[str, tuple] = {}      # path -> (ent, exp)
        self.revokes_seen = 0      # observability (tests/metrics)
        self.rados = RadosClient(mon_addr, name, auth=auth,
                                 secure=secure).connect()
        info = self._req("mount", {"client": self.client_id})
        self.block_size = info["block_size"]
        self.data = self.rados.open_ioctx(info["data_pool"])
        self._apply_snapc(info.get("snapc"),
                          info.get("snap_epoch", 0))
        # a snapc broadcast may have raced ahead of self.data existing;
        # apply the buffered one if it is newer than the mount's
        with self._lock:
            early = self._early_snapc
            self._early_snapc = None
        if early is not None:
            self._apply_snapc(early[1], early[0])

    def shutdown(self) -> None:
        self.messenger.shutdown()
        self.rados.shutdown()

    # -- MDS RPC -------------------------------------------------------------

    def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, M.MClientReply):
            with self._lock:
                w = self._waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()
        elif isinstance(msg, M.MClientCaps) and msg.op == "snapc":
            # a snapshot was created/removed: writes must carry the
            # new SnapContext so the OSDs COW data objects.  msg.seq
            # carries the MDS's snap epoch (ordering across racing
            # broadcasts and the mount reply).
            import json as _json
            try:
                snapc = _json.loads(msg.caps)
            except ValueError:
                return
            with self._lock:
                pre_mount = self.data is None
                if pre_mount and (self._early_snapc is None or
                                  msg.seq > self._early_snapc[0]):
                    self._early_snapc = (msg.seq, snapc)
            if not pre_mount:
                self._apply_snapc(snapc, msg.seq)
        elif isinstance(msg, M.MClientCaps) and msg.op == "revoke":
            # flush + ack on a worker: this runs on the mds_conn reader
            # thread, and the flush's own RPC reply must be readable
            threading.Thread(target=self._handle_revoke, args=(msg,),
                             daemon=True, name="fs-cap-revoke").start()

    def _handle_revoke(self, msg: M.MClientCaps) -> None:
        """MDS took our cache cap: write back dirty state, drop caches,
        ack with the reduced cap set (reference Client::handle_caps
        CEPH_CAP_OP_REVOKE)."""
        self.revokes_seen += 1
        flush = {"ino": msg.ino, "seq": msg.seq, "caps": msg.caps,
                 "client": self.client_id}
        with self._lock:
            self._caps[msg.ino] = msg.caps
            self._cap_seqs[msg.ino] = max(
                msg.seq, self._cap_seqs.get(msg.ino, 0))
            files = list(self._files.get(msg.ino, ()))
            self._stat_cache = {p: v for p, v in
                                self._stat_cache.items()
                                if v[0].get("ino") != msg.ino}
            # size snapshot + tick must be ONE atomic step under the
            # lock: a concurrent write-through flush snapshots under
            # the same lock, so tick order == snapshot order and the
            # MDS can safely drop the older of the two
            dirty = [f for f in files if f._dirty]
            if dirty:
                # several handles on one inode: the file's logical
                # size is the furthest any handle wrote
                flush["path"] = dirty[0].path
                flush["size"] = max(f.size for f in dirty)
                flush["mtime"] = time.time()
                self._attr_tick += 1
                flush["tick"] = self._attr_tick
        try:
            self._req("cap_flush", flush)
        except FSError:
            return   # MDS drops our caps on timeout; keep _dirty set
        for f in dirty:
            f._dirty = False

    def _req_raw(self, conn, op: str, args: dict,
                 timeout: float = 30.0):
        with self._lock:
            self._tid += 1
            tid = self._tid
            w = {"event": threading.Event(), "reply": None}
            self._waiters[tid] = w
        conn.send_message(M.MClientRequest(op, args, tid))
        if not w["event"].wait(timeout):
            with self._lock:             # no reply will ever pop it:
                self._waiters.pop(tid, None)   # reclaim the waiter
            raise FSError(110, f"mds request {op} timed out")
        return w["reply"]

    def _conn_for(self, addr: tuple):
        """Connection to another MDS rank (multi-MDS redirects); mounts
        a session on first use so caps/revokes work against that rank."""
        with self._lock:
            conn = self._mds_conns.get(addr)
        if conn is not None:
            return conn
        conn = self.messenger.connect(addr)
        reply = self._req_raw(conn, "mount",
                              {"client": self.client_id})
        if reply.result != 0:
            raise FSError(-reply.result, "mount on redirect target")
        with self._lock:
            self._mds_conns[addr] = conn
        return conn

    def _req(self, op: str, args: dict, timeout: float = 30.0) -> dict:
        """MDS RPC with multi-MDS handling: ESTALE+redirect_addr sends
        the op to the owning rank (reference client MDS-session
        retargeting on auth hints); EAGAIN (subtree frozen by a
        migration, or a transient server retry limit) backs off and
        retries until the authority settles."""
        import errno as _e
        conn = self.mds_conn
        cur_addr = None                  # non-None = redirected conn
        # last-known-owner cache: ops under an exported subtree go
        # straight to the owning rank instead of paying a permanent
        # ESTALE redirect hop through the primary every time
        route_key = args.get("path") or args.get("dst")
        cached = self._route_cache.get(route_key) \
            if route_key else None
        if cached is not None:
            try:
                conn = self._conn_for(cached)
                cur_addr = cached
            except FSError:
                self._route_cache.pop(route_key, None)
        redirects = 0
        deadline = time.time() + timeout
        while True:
            try:
                attempt = min(timeout, 10.0) if cur_addr else timeout
                reply = self._req_raw(conn, op, args, attempt)
            except FSError as e:
                if e.errno == 110 and cur_addr is not None and \
                        time.time() < deadline:
                    # the redirect target died: drop the cached conn
                    # and re-resolve authority from the primary (the
                    # surviving rank auto-takes-over dead subtrees)
                    with self._lock:
                        self._mds_conns.pop(cur_addr, None)
                    if route_key:
                        self._route_cache.pop(route_key, None)
                    conn, cur_addr = self.mds_conn, None
                    continue
                raise
            if reply.result == 0:
                if route_key and cur_addr is not None:
                    if len(self._route_cache) > 4096:
                        self._route_cache.clear()
                    self._route_cache[route_key] = cur_addr
                return reply.out
            if reply.result == -_e.ESTALE and \
                    reply.out.get("redirect_addr"):
                redirects += 1
                if redirects > 8:
                    raise FSError(_e.ELOOP, f"redirect loop on {op}")
                if route_key:
                    self._route_cache.pop(route_key, None)
                cur_addr = tuple(reply.out["redirect_addr"])
                conn = self._conn_for(cur_addr)
                continue
            if reply.result == -_e.EAGAIN and time.time() < deadline:
                time.sleep(0.2)
                continue
            raise FSError(-reply.result,
                          reply.out.get("error", op))

    # -- namespace -----------------------------------------------------------

    def stat(self, path: str) -> dict:
        parts = [p for p in path.split("/") if p]
        if parts and parts[-1] == ".snap":
            # the .snap virtual directory itself
            from .mds import S_IFDIR
            self.snap_list("/" + "/".join(parts[:-1]))  # ENOENT check
            return {"ino": 0, "mode": S_IFDIR | 0o555, "size": 0,
                    "mtime": 0}
        snap = self._split_snap(path)
        if snap is not None:
            dirpath, name, rel = snap
            return self._req("snap_resolve", {
                "path": dirpath, "name": name, "rel": rel})["ent"]
        norm = _norm(path)
        with self._lock:
            hit = self._stat_cache.get(norm)
            if hit is not None and hit[1] > time.time():
                return dict(hit[0])
        ent = self._req("stat", {"path": path})["ent"]
        # cache only under the "c" cap, RE-checked under the lock at
        # insert time: a revoke landing between the RPC and here has
        # already purged the cache and must not be undone by a stale
        # re-insert
        with self._lock:
            if "c" in self._caps.get(ent.get("ino"), ""):
                self._stat_cache[norm] = (dict(ent),
                                          time.time() + LEASE_TTL)
        return ent

    def export_dir(self, path: str, to_rank: str) -> dict:
        """Migrate a subtree's authority to another MDS rank
        (redirect-routed to the current owner like any path op)."""
        return self._req("export_dir", {"path": path, "to": to_rank})

    def mkdir(self, path: str) -> None:
        self._req("mkdir", {"path": path})

    def makedirs(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        for i in range(1, len(parts) + 1):
            try:
                self.mkdir("/".join(parts[:i]))
            except FSError as e:
                if e.errno != 17:   # EEXIST
                    raise

    def readdir(self, path: str) -> list[tuple[str, dict]]:
        parts = [p for p in path.split("/") if p]
        if parts and parts[-1] == ".snap":
            # listing the .snap virtual dir enumerates snapshot names
            from .mds import S_IFDIR
            dirpath = "/" + "/".join(parts[:-1])
            return [(n, {"ino": 0, "mode": S_IFDIR | 0o555,
                         "size": 0, "mtime": 0})
                    for n in self.snap_list(dirpath)]
        snap = self._split_snap(path)
        if snap is not None:
            dirpath, name, rel = snap
            out = self._req("snap_resolve", {
                "path": dirpath, "name": name, "rel": rel})
            return [(k, m) for k, m in out.get("entries", [])]
        out = self._req("readdir", {"path": path})
        return [(k, m) for k, m in out["entries"]]

    def _apply_snapc(self, snapc, epoch: int = 0) -> None:
        """Route the fs SnapContext onto the data ioctx (reference
        client snap realm update): [seq, [ids desc]] or None.  Epochs
        order racing updates — an older broadcast must not clobber a
        newer one."""
        with self._lock:
            if epoch < self._snap_epoch:
                return
            self._snap_epoch = epoch
            if snapc and snapc[1]:
                self.data.snapc = [int(snapc[0]),
                                   [int(s) for s in snapc[1]]]
            else:
                self.data.snapc = None

    @staticmethod
    def _split_snap(path: str):
        """path/.snap/<name>/<rel> -> (dirpath, name, rel) or None."""
        parts = [p for p in path.split("/") if p]
        if ".snap" not in parts:
            return None
        i = parts.index(".snap")
        if i + 1 >= len(parts):
            return None
        return ("/" + "/".join(parts[:i]), parts[i + 1],
                "/".join(parts[i + 2:]))

    def snap_create(self, dirpath: str, name: str) -> None:
        """Snapshot a directory subtree (reference mkdir .snap/<name>)."""
        out = self._req("snap_create", {"path": dirpath, "name": name})
        self._apply_snapc(out.get("snapc"), out.get("snap_epoch", 0))

    def snap_rm(self, dirpath: str, name: str) -> None:
        out = self._req("snap_rm", {"path": dirpath, "name": name})
        self._apply_snapc(out.get("snapc"), out.get("snap_epoch", 0))

    def snap_list(self, dirpath: str) -> list[str]:
        return self._req("snap_list", {"path": dirpath})["snaps"]

    def _uncache(self, *paths: str, subtree: bool = False) -> None:
        """Our own namespace mutations invalidate the lease cache: no
        revoke arrives for them (we ARE the holder).  subtree=True
        also evicts every cached descendant — renaming/removing a
        directory must not leave stat hits live under the old name
        for up to LEASE_TTL."""
        with self._lock:
            for p in paths:
                np = _norm(p)
                self._stat_cache.pop(np, None)
                if subtree:
                    pre = np.rstrip("/") + "/"
                    for c in [c for c in self._stat_cache
                              if c.startswith(pre)]:
                        self._stat_cache.pop(c, None)

    def unlink(self, path: str) -> None:
        self._req("unlink", {"path": path})
        self._uncache(path)

    def rmdir(self, path: str) -> None:
        self._req("rmdir", {"path": path})
        self._uncache(path, subtree=True)

    def rename(self, src: str, dst: str) -> None:
        self._req("rename", {"src": src, "dst": dst})
        self._uncache(src, dst, subtree=True)

    # -- file I/O ------------------------------------------------------------

    def open(self, path: str, mode: str = "r") -> "File":
        snap = self._split_snap(path)
        if snap is not None:
            if "w" in mode or "a" in mode or "+" in mode:
                raise FSError(30, f"{path}: snapshots are read-only")
            dirpath, name, rel = snap
            out = self._req("snap_resolve", {
                "path": dirpath, "name": name, "rel": rel})
            from .mds import S_IFDIR
            if out["ent"]["mode"] & S_IFDIR:
                raise FSError(21, path)
            return File(self, path, out["ent"], snap_id=out["snapid"])
        writing = "w" in mode or "a" in mode or "+" in mode
        # POSIX fopen: w/w+/a/a+ create; r/r+ require existence
        out = self._req("open", {
            "path": path, "client": self.client_id,
            "want": "rw" if writing else "r",
            "create": "w" in mode or "a" in mode})
        ent, caps = out["ent"], out.get("caps", "")
        with self._lock:
            # a revoke that raced in after the MDS granted (higher
            # seq) must not be clobbered by this stale grant
            seq = out.get("cap_seq", 0)
            if seq >= self._cap_seqs.get(ent["ino"], 0):
                self._caps[ent["ino"]] = caps
                self._cap_seqs[ent["ino"]] = seq
        f = File(self, path, ent)
        with self._lock:
            self._files.setdefault(ent["ino"], []).append(f)
        if "w" in mode and ent.get("size", 0):
            f.truncate(0)
        if "a" in mode:
            f.pos = f.size
        return f

    def write_file(self, path: str, data: bytes) -> None:
        with self.open(path, "w") as f:
            f.write(data)

    def read_file(self, path: str) -> bytes:
        with self.open(path, "r") as f:
            return f.read()


class File:
    """An open file handle (reference Fh): striped block I/O against
    the data pool; size/mtime pushed to the MDS on flush/close."""

    def __init__(self, fs: CephFS, path: str, ent: dict,
                 snap_id: int = 0):
        self.fs = fs
        self.path = path
        self.ino = ent["ino"]
        self.size = ent.get("size", 0)
        self.pos = 0
        self.snap_id = snap_id      # >0: read-only snapshot view
        self._dirty = False

    # -- striping ------------------------------------------------------------

    def pwrite(self, data: bytes, offset: int) -> int:
        if self.snap_id:
            raise FSError(30, f"{self.path}: snapshot is read-only")
        bs = self.fs.block_size
        off = offset
        view = memoryview(data)
        while view:
            blk, in_blk = divmod(off, bs)
            n = min(bs - in_blk, len(view))
            self.fs.data.write(data_oid(self.ino, blk),
                               bytes(view[:n]), offset=in_blk)
            view = view[n:]
            off += n
        self.size = max(self.size, offset + len(data))
        self._dirty = True
        # without the "c" cap another client holds caps on this inode:
        # write attrs through so it observes our size promptly
        if "c" not in self.fs._caps.get(self.ino, ""):
            self.flush()
        return len(data)

    def pread(self, length: int, offset: int) -> bytes:
        bs = self.fs.block_size
        end = min(offset + length, self.size)
        if end <= offset:
            return b""
        out = bytearray()
        off = offset
        while off < end:
            blk, in_blk = divmod(off, bs)
            n = min(bs - in_blk, end - off)
            from ..rados.client import RadosError
            try:
                piece = self.fs.data.read(data_oid(self.ino, blk),
                                          n, offset=in_blk,
                                          snap=self.snap_id)
            except RadosError as e:
                if e.errno != 2:   # only ENOENT is a sparse hole
                    # a cluster fault must not read back as zeros
                    raise FSError(e.errno, f"read {self.path}") from e
                piece = b""
            out += piece.ljust(n, b"\x00")
            off += n
        return bytes(out)

    # -- posix-ish surface ---------------------------------------------------

    def write(self, data: bytes) -> int:
        n = self.pwrite(data, self.pos)
        self.pos += n
        return n

    def read(self, length: int | None = None) -> bytes:
        if length is None:
            length = self.size - self.pos
        out = self.pread(length, self.pos)
        self.pos += len(out)
        return out

    def seek(self, pos: int) -> None:
        self.pos = pos

    def truncate(self, size: int) -> None:
        if self.snap_id:
            raise FSError(30, f"{self.path}: snapshot is read-only")
        bs = self.fs.block_size
        from ..rados.client import RadosError
        old_blocks = -(-max(self.size, 1) // bs)
        keep_blocks = -(-size // bs) if size else 0
        for b in range(keep_blocks, old_blocks):
            try:
                self.fs.data.remove(data_oid(self.ino, b))
            except RadosError:
                pass
        if size and size % bs:
            try:
                self.fs.data.truncate(data_oid(self.ino,
                                               keep_blocks - 1),
                                      size % bs)
            except RadosError:
                pass
        self.size = size
        self._dirty = True
        # same shared-mode write-through rule as pwrite: contenders
        # must observe the truncated size promptly
        if "c" not in self.fs._caps.get(self.ino, ""):
            self.flush()

    def flush(self) -> None:
        if self._dirty:
            with self.fs._lock:    # atomic (size, tick) snapshot
                self.fs._attr_tick += 1
                args = {"path": self.path, "size": self.size,
                        "mtime": time.time(),
                        "client": self.fs.client_id,
                        "tick": self.fs._attr_tick}
            self.fs._req("setattr", args)
            self._dirty = False
            with self.fs._lock:
                self.fs._stat_cache.pop(_norm(self.path), None)

    def close(self) -> None:
        self.flush()
        with self.fs._lock:
            files = self.fs._files.get(self.ino, [])
            if self in files:
                files.remove(self)
            last = not files
        if last:
            try:
                self.fs._req("cap_release", {
                    "ino": self.ino, "client": self.fs.client_id})
            except FSError:
                pass
            with self.fs._lock:
                self.fs._caps.pop(self.ino, None)
                # no caps -> no right to serve cached stats: another
                # client can now mutate without any revoke reaching us
                self.fs._stat_cache = {
                    p: v for p, v in self.fs._stat_cache.items()
                    if v[0].get("ino") != self.ino}

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
