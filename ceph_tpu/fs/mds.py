"""MDS: the metadata server daemon.

Re-expresses the reference src/mds/ at the fidelity the namespace
needs (MDSRank dispatch of MClientRequest -> Server::handle_client_*,
src/mds/Server.cc):

- The namespace lives in a METADATA POOL: one directory object per
  directory inode ("dir.<ino>"), entries maintained server-side by the
  generic directory object class (reference CDir dirfrags as omap
  objects; cls-side updates make each dentry mutation atomic).  Child
  inode attributes are EMBEDDED in the parent's dentry (reference
  stores inodes in dentries the same way — no separate inode objects
  on the common path).
- File data is NOT proxied: clients write striped blocks straight to
  the data pool; the MDS only records size/mtime reported back by the
  client (the reduced form of the reference's client-caps size
  recall).
- Inode numbers come from a persisted allocator object (reference
  InoTable).
- Multi-step mutations (mkdir/unlink/rmdir/rename) journal a redo
  INTENT to the MDLog before touching directory objects and replay it
  on restart (reference MDLog + journal/; see mdlog.py) — an MDS
  killed mid-rename comes back to a consistent namespace.
- FS snapshots (reference SnapServer + the .snap virtual directory,
  reduced): snapshotting a directory allocates a RADOS selfmanaged
  snap id for the data pool — file DATA is COW'd by the OSDs at zero
  copy cost, exactly like RBD snapshots — and records an EAGER copy of
  the subtree namespace in a snap registry object (the reference COWs
  dentries lazily; eager manifest is the reduced form, O(subtree) at
  snap time).  Clients learn the new SnapContext through an MClientCaps
  "snapc" broadcast so subsequent writes clone; reads under
  path/.snap/<name>/... resolve against the manifest at the recorded
  snap id.  In-flight writes racing the broadcast land pre-snap
  (documented reduction of the reference's cap-revoke quiesce).
- File capabilities (reference Locker.h / Capability.h, reduced):
  open grants caps per (ino, session) — "r"ead, "w"rite, and "c"ache
  (the right to cache attrs and buffer size updates client-side,
  granted only to a SOLE opener).  A second client opening the same
  inode triggers revocation: the MDS sends MClientCaps revoke, the
  holder flushes dirty size/mtime and acks (op cap_flush), and only
  then is the new open granted — so contending clients always observe
  each other's flushed state.

Locking: each active MDS owns the subtrees the SUBTREE MAP assigns it
(reference MDSRank auth + subtree partitioning); within a rank,
per-directory striped locks serialize multi-step ops (rename takes
both directory locks in ino order).

Multi-MDS (reference Migrator.cc / MDBalancer, idiomatically reduced):
because dirfrags live IN RADOS (not in MDS memory), migrating a subtree
moves AUTHORITY, not metadata — export freezes the subtree (EAGAIN to
clients, who retry), flushes/revokes client caps under it, then commits
ONE atomic subtree-map update; the importer has nothing to import.  A
donor crash mid-export recovers from its mdlog intent: the map update
is the commit point, so the export either happened or it didn't.
Clients reaching the wrong rank get a redirect with the owner's addr
(reference forward/auth hints).  Rank failover: a surviving MDS
`mds_takeover`s a dead peer — probes its address, replays the peer's
pending mdlog intents, and adopts its subtrees in the map.
"""

from __future__ import annotations

import errno
import json
import threading
import time

from ..msg import Messenger
from ..msg import messages as M
from ..rados.client import RadosError

META_POOL = "cephfs_metadata"
DATA_POOL = "cephfs_data"
ROOT_INO = 1
INOTABLE_OBJ = "mds_inotable"
SNAP_REGISTRY = "mds_snaptable"
SUBTREE_OBJ = "mds_subtreemap"

S_IFDIR = 0o040000
S_IFREG = 0o100000


def data_oid(ino: int, block: int) -> str:
    """reference file layout object naming: <ino hex>.<block hex>."""
    return f"{ino:016x}.{block:08x}"


class MDSDaemon:
    def __init__(self, mon_addr, addr=("127.0.0.1", 0),
                 block_size: int = 1 << 22, auth=None,
                 secure: bool = False, ec_profile: str | None = None,
                 pg_num: int = 8, name: str = "a",
                 fs_name: str = "cephfs"):
        from ..rados import RadosClient
        self.block_size = block_size
        self.name = name
        self.fs_name = fs_name
        self.client = RadosClient(mon_addr, "mds", auth=auth,
                                  secure=secure).connect()
        self._ensure_pools(ec_profile, pg_num)
        self.meta = self.client.open_ioctx(META_POOL)
        self.data = self.client.open_ioctx(DATA_POOL)
        self._locks = [threading.Lock() for _ in range(64)]
        self._ino_lock = threading.Lock()
        self._mkfs()
        # capability + snapshot state first: mdlog replay may purge
        # data, which consults the snapc (reference Locker/Capability,
        # SnapServer — both reduced)
        self._sessions: dict[str, object] = {}      # client id -> conn
        self._caps: dict[int, dict[str, str]] = {}  # ino -> {sess: caps}
        self._cap_lock = threading.Lock()
        self._cap_seq = 0
        self._snapc_cache: list | None = None
        self._snap_epoch = 0
        self._flush_waiters: dict[tuple, threading.Event] = {}
        # multi-MDS state: subtree authority + migration freezes —
        # initialized BEFORE the mdlog replay, whose rename_cross
        # handler consults the fsmap/peer machinery
        self.rank = name
        # frozen prefixes: an immutable snapshot REPLACED on change, so
        # gate reads never race an in-place mutation from the export
        # thread
        self._frozen: frozenset[str] = frozenset()
        self._subtree_cache: tuple[float, dict] | None = None
        self._fsmap_cache: tuple[float, dict] | None = None
        self._probe_cache: dict[str, tuple[float, bool]] = {}
        self._takeover_lock = threading.Lock()
        self._inflight = 0                   # gated path-ops in flight
        self._inflight_lock = threading.Lock()
        self._peer_tid = 0                   # MDS->MDS slave requests
        self._peer_waiters: dict[int, dict] = {}
        self.ops_served = 0                  # observability (tests)
        from .mdlog import MDLog
        # log keyed by MDS name: a restart under the same name replays
        # its own intents; a concurrently-booted second MDS must NOT
        # replay (and delete) a live peer's in-flight intents.  A DEAD
        # peer's log is replayed by whoever runs mds_takeover.
        self.mdlog = MDLog(self.meta, rank=name)
        self._replay_mdlog()
        self._bootstrap_subtree_map()
        self.messenger = Messenger("mds", auth=auth, secure=secure)
        self.messenger.add_dispatcher(self._dispatch)
        self.addr = self.messenger.bind(addr)
        self._register_fsmap()

    def _register_fsmap(self) -> None:
        """Put this filesystem + MDS into the mon's replicated fsmap
        (reference MDSMonitor: an MDS exists only through the FSMap).
        Best-effort: a mon predating the fs commands must not block
        the data path."""
        try:
            r, _ = self.client.mon_command({
                "prefix": "fs new", "name": self.fs_name,
                "metadata_pool": META_POOL, "data_pool": DATA_POOL})
            import errno as _e
            if r not in (0, -_e.EEXIST):
                return
            self.client.mon_command({
                "prefix": "mds boot", "name": self.name,
                "fs": self.fs_name, "addr": list(self.addr)})
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        self.messenger.shutdown()
        self.client.shutdown()

    def _ensure_pools(self, ec_profile, pg_num) -> None:
        for name, kind in ((META_POOL, "replicated"),
                           (DATA_POOL,
                            "erasure" if ec_profile else "replicated")):
            try:
                kw = {"pg_num": pg_num}
                if kind == "erasure":
                    kw["erasure_code_profile"] = ec_profile
                else:
                    kw["size"] = 2
                self.client.create_pool(name, kind, **kw)
            except RadosError as e:
                if e.errno != errno.EEXIST:
                    raise

    def _mkfs(self) -> None:
        """Create the root directory + inode table if absent."""
        self.meta.execute(f"dir.{ROOT_INO:x}", "rgw", "dir_init", b"")
        try:
            raw = self.meta.read(INOTABLE_OBJ, 0)
        except RadosError:
            raw = b""
        if raw:
            self._next_ino = json.loads(raw.decode())["next"]
        else:
            self._next_ino = ROOT_INO + 1
            self._persist_inotable()

    def _persist_inotable(self) -> None:
        self.meta.write_full(INOTABLE_OBJ, json.dumps(
            {"next": self._next_ino}).encode())

    def _alloc_ino(self) -> int:
        with self._ino_lock:
            ino = self._next_ino
            self._next_ino += 1
            self._persist_inotable()
            return ino

    # -- dir object helpers --------------------------------------------------

    def _dir_lock(self, ino: int) -> threading.Lock:
        return self._locks[ino % len(self._locks)]

    def _dget(self, dino: int, name: str) -> dict | None:
        try:
            raw = self.meta.execute(
                f"dir.{dino:x}", "rgw", "dir_get",
                json.dumps({"key": name}).encode())
        except RadosError as e:
            if e.errno == errno.ENOENT:
                return None
            raise
        return json.loads(raw.decode())

    def _dset(self, dino: int, name: str, ent: dict) -> None:
        self.meta.execute(f"dir.{dino:x}", "rgw", "dir_add",
                          json.dumps({"key": name, "meta": ent}).encode())

    def _drm(self, dino: int, name: str) -> None:
        self.meta.execute(f"dir.{dino:x}", "rgw", "dir_rm",
                          json.dumps({"key": name}).encode())

    def _dlist(self, dino: int) -> list:
        raw = self.meta.execute(
            f"dir.{dino:x}", "rgw", "dir_list",
            json.dumps({"max": 100000}).encode())
        return json.loads(raw.decode())["entries"]

    def _dcount(self, dino: int) -> int:
        return int(self.meta.execute(f"dir.{dino:x}", "rgw",
                                     "dir_count", b""))

    # -- subtree authority (reference MDCache subtree map + Migrator) -------

    @staticmethod
    def _norm(path: str) -> str:
        parts = [p for p in path.split("/") if p]
        return "/" + "/".join(parts)

    def _bootstrap_subtree_map(self) -> None:
        """First active MDS claims the root subtree.  (A simultaneous
        first-boot of two MDSes could race the claim; deployments boot
        rank 0 first, like the reference's rank-0 creation.)"""
        self.meta.execute(SUBTREE_OBJ, "rgw", "dir_init", b"")
        try:
            self.meta.execute(SUBTREE_OBJ, "rgw", "dir_get",
                              json.dumps({"key": "/"}).encode())
        except RadosError:
            self.meta.execute(SUBTREE_OBJ, "rgw", "dir_add", json.dumps(
                {"key": "/", "meta": {"rank": self.rank}}).encode())

    def _load_subtrees(self, force: bool = False) -> dict[str, str]:
        now = time.time()
        if not force and self._subtree_cache is not None and \
                now - self._subtree_cache[0] < 1.0:
            return self._subtree_cache[1]
        raw = self.meta.execute(SUBTREE_OBJ, "rgw", "dir_list",
                                json.dumps({"max": 10000}).encode())
        m = {k: v["rank"]
             for k, v in json.loads(raw.decode())["entries"]}
        self._subtree_cache = (now, m)
        return m

    def _authority(self, path: str) -> str:
        """Longest-prefix owner of `path`.  "mine" from a fresh-enough
        cache is trustworthy (this rank updates its own cache
        synchronously when it exports); "not mine" forces a refresh
        before redirecting, so an importer serves as soon as the map
        commits."""
        path = self._norm(path)

        def owner_in(m):
            best, best_len = None, -1
            for prefix, rank in m.items():
                p = prefix.rstrip("/") or "/"
                if (path == p or path.startswith(p + "/") or
                        p == "/") and len(p) > best_len:
                    best, best_len = rank, len(p)
            return best

        owner = owner_in(self._load_subtrees())
        if owner != self.rank:
            owner = owner_in(self._load_subtrees(force=True))
        return owner

    def _fs_mds_map(self, force: bool = False) -> dict:
        now = time.time()
        if not force and self._fsmap_cache is not None and \
                now - self._fsmap_cache[0] < 2.0:
            return self._fsmap_cache[1]
        try:
            _r, out = self.client.mon_command({"prefix": "fs dump"})
            m = out["filesystems"].get(self.fs_name, {}).get("mds", {})
        except Exception:  # noqa: BLE001 - mon electing
            m = (self._fsmap_cache or (0, {}))[1]
        self._fsmap_cache = (now, m)
        return m

    def _mds_addr(self, rank: str,
                  force: bool = False) -> tuple | None:
        ent = self._fs_mds_map(force).get(rank)
        if ent and ent.get("addr"):
            return tuple(ent["addr"])
        return None

    def _peer_alive(self, rank: str, addr: tuple) -> bool:
        import socket
        now = time.time()
        hit = self._probe_cache.get(rank)
        if hit is not None and now - hit[0] < 2.0:
            return hit[1]
        try:
            with socket.create_connection(tuple(addr), timeout=0.5):
                alive = True
        except OSError:
            alive = False
        self._probe_cache[rank] = (now, alive)
        return alive

    def _authority_gate(self, path: str,
                        allow_foreign: bool = False) -> str | None:
        owner = self._authority(path)
        if owner == self.rank or owner is None:
            return None
        if allow_foreign:
            return owner
        addr = self._mds_addr(owner) or self._mds_addr(owner,
                                                       force=True)
        if addr is not None and self._peer_alive(owner, addr):
            raise _Redirect(owner, addr)
        # recorded owner is dead or unknown: adopt its subtrees and
        # serve (auto-failover; the reference drives this from mon
        # beacons + standby promotion — the probe+takeover form is the
        # reduced single-host equivalent, split-brain caveat documented
        # in _handle_takeover)
        self._handle_takeover({"rank": owner, "force": True})

    def _frozen_gate(self, path: str) -> None:
        path = self._norm(path)
        for fz in self._frozen:
            if path == fz or path.startswith(fz + "/") or fz == "/":
                raise _Err(errno.EAGAIN, f"subtree {fz} migrating")

    def _subtree_inos(self, dino: int) -> list[int]:
        out = [dino]
        for name, ent in self._dlist(dino):
            if name.startswith("@"):
                continue
            if ent.get("mode", 0) & S_IFDIR:
                out.extend(self._subtree_inos(ent["ino"]))
            else:
                out.append(ent["ino"])
        return out

    def _handle_export_dir(self, a: dict) -> dict:
        """Migrate authority over a subtree to another rank (reference
        Migrator::export_dir, collapsed to an authority hand-off —
        see the module docstring).  `hold_s` is a test hook that holds
        the freeze window open."""
        path = self._norm(a["path"])
        to = a["to"]
        if self._authority(path) != self.rank:
            raise _Err(errno.EINVAL, f"{path} not owned by this rank")
        if to != self.rank and self._mds_addr(to, force=True) is None:
            raise _Err(errno.ENOENT, f"no such mds {to!r}")
        _, ent = self._resolve(path)
        if not ent["mode"] & S_IFDIR:
            raise _Err(errno.ENOTDIR, path)
        ev = {"op": "export", "path": path, "to": to}
        seq = self.mdlog.append(ev)
        with self._inflight_lock:        # RMW of the snapshot is
            self._frozen = self._frozen | {path}   # serialized
        # drain: ops admitted BEFORE the freeze may still be mutating
        # the subtree; the map must not commit under their feet
        # (reference Migrator waits for in-flight requests)
        deadline = time.time() + 10.0
        while self._inflight > 0 and time.time() < deadline:
            time.sleep(0.02)
        try:
            # cap migration, reduced: flush + revoke every cap under
            # the subtree so dirty state reaches the shared pool before
            # authority moves; clients re-open against the new owner
            # on their next op (via redirect)
            for ino in self._subtree_inos(ent["ino"]):
                with self._cap_lock:
                    holders = list(self._caps.get(ino, {}))
                for sess in holders:
                    with self._cap_lock:
                        self._cap_seq += 1
                        seq_r = self._cap_seq
                    self._revoke(sess, ino, "", seq_r)
            if a.get("hold_s"):
                time.sleep(float(a["hold_s"]))
            # THE commit point: one atomic map update
            self.meta.execute(SUBTREE_OBJ, "rgw", "dir_add", json.dumps(
                {"key": path, "meta": {"rank": to}}).encode())
            self._subtree_cache = None
        finally:
            with self._inflight_lock:
                self._frozen = self._frozen - {path}
        self.mdlog.mark_done(seq)
        return {"exported": path, "to": to}

    def _handle_takeover(self, a: dict) -> dict:
        """Adopt a dead peer's subtrees + replay its pending mdlog
        intents (rank failover; reference standby takeover +
        MDCache::resolve).  force=True skips the liveness probe — the
        probe guards the common case, but a partitioned-yet-alive peer
        could still be usurped (the reference closes this with mon
        fencing/blacklist; documented reduction)."""
        peer = a["rank"]
        if peer == self.rank:
            raise _Err(errno.EINVAL, "cannot take over self")
        addr = self._mds_addr(peer, force=True)
        if addr is not None and not a.get("force"):
            import socket
            try:
                with socket.create_connection(addr, timeout=1.0):
                    raise _Err(errno.EBUSY, f"mds {peer} is alive")
            except OSError:
                pass              # unreachable: proceed
        from .mdlog import MDLog
        with self._takeover_lock:
            peer_log = MDLog(self.meta, rank=peer)
            replayed = 0
            for seq, ev in peer_log.pending():
                self._apply_event(ev)
                peer_log.mark_done(seq)
                replayed += 1
            adopted = []
            for prefix, owner in self._load_subtrees(
                    force=True).items():
                if owner == peer:
                    self.meta.execute(
                        SUBTREE_OBJ, "rgw", "dir_add", json.dumps(
                            {"key": prefix,
                             "meta": {"rank": self.rank}}).encode())
                    adopted.append(prefix)
            self._subtree_cache = None
            return {"adopted": adopted, "replayed": replayed}

    # -- MDS-to-MDS slave requests (reference Server slave ops /
    #    Migrator peer messages, reduced) ------------------------------------

    def _peer_request(self, rank: str, op: str, args: dict,
                      timeout: float = 10.0) -> dict:
        addr = self._mds_addr(rank) or self._mds_addr(rank, force=True)
        if addr is None:
            raise _Err(errno.EIO, f"peer mds {rank} unknown")
        conn = self.messenger.connect(tuple(addr))
        with self._inflight_lock:
            self._peer_tid += 1
            tid = self._peer_tid
            w = {"event": threading.Event(), "reply": None}
            self._peer_waiters[tid] = w
        conn.send_message(M.MClientRequest(op, args, tid))
        if not w["event"].wait(timeout):
            with self._inflight_lock:
                self._peer_waiters.pop(tid, None)
            raise _Err(errno.EIO, f"peer mds {rank} timed out")
        r = w["reply"]
        if r.result != 0:
            raise _Err(-r.result, f"peer {op}: {r.out.get('error')}")
        return r.out

    def _handle_peer_drm(self, a: dict) -> dict:
        """Slave half of a cross-rank rename: remove a dentry from a
        dirfrag THIS rank owns, on behalf of the dst owner.  Guarded by
        the expected ino so a racing local mutation is never clobbered
        (reference rmdir/rename witness ops)."""
        dino, name = a["dino"], a["name"]
        with self._dir_lock(dino):
            cur = self._dget(dino, name)
            if cur is not None and cur["ino"] == a["ino"]:
                self._drm(dino, name)
        return {}

    def _rename_cross(self, a: dict, src_owner: str) -> dict:
        """Cross-rank rename: this rank owns dst; the src dentry is
        removed THROUGH its owner.  The intent is journaled here, so a
        crash between the local link and the peer removal replays to
        completion — never a doubled entry that stays.

        Lock order: the dst dir lock covers ONLY the journal append and
        the local dst link; the peer_drm call runs after it is
        released.  Holding it across the peer request inverted the
        distributed lock order — two opposite-direction cross-rank
        renames each held their own dst dir lock while the peer's
        handler blocked on taking it, stalling both until the 10s peer
        timeout.  Releasing first is safe: the journaled intent already
        commits the rename, and _handle_peer_drm is ino-guarded, so a
        racing local mutation of the src dentry is never clobbered."""
        sdino, sname = self._split(a["src"])
        ddino, dname = self._split(a["dst"])
        with self._dir_lock(ddino):
            ent = self._dget(sdino, sname)   # read-only peek is safe
            if ent is None:
                raise _Err(errno.ENOENT, a["src"])
            existing = self._dget(ddino, dname)
            replaced = None
            if existing is not None:
                if existing["mode"] & S_IFDIR:
                    raise _Err(errno.EISDIR, a["dst"])
                if existing["ino"] != ent["ino"]:
                    replaced = existing
            ev = {"op": "rename_cross", "sdino": sdino, "sname": sname,
                  "ddino": ddino, "dname": dname, "ent": ent,
                  "replaced": replaced, "src_owner": src_owner}
            seq = self.mdlog.append(ev)
            self._dset(ddino, dname, ent)
        # if the peer call fails the intent stays pending and the
        # removal completes on replay/takeover
        self._peer_request(src_owner, "peer_drm", {
            "dino": sdino, "name": sname, "ino": ent["ino"]})
        if replaced is not None:
            self._purge_data(replaced)
        self.mdlog.mark_done(seq)
        return {}

    # -- path walking (reference Server::rdlock_path_pin_ref) ---------------

    def _resolve(self, path: str) -> tuple[int, dict]:
        """Path -> (parent dir ino of the LAST component, entry dict of
        the full path).  Root resolves to a synthetic dir entry."""
        parts = [p for p in path.split("/") if p]
        cur = {"ino": ROOT_INO, "mode": S_IFDIR, "size": 0, "mtime": 0}
        dino = ROOT_INO
        for i, name in enumerate(parts):
            if not cur["mode"] & S_IFDIR:
                raise _Err(errno.ENOTDIR, "/".join(parts[:i]))
            dino = cur["ino"]
            ent = self._dget(dino, name)
            if ent is None:
                raise _Err(errno.ENOENT, "/".join(parts[: i + 1]))
            cur = ent
        return dino, cur

    def _split(self, path: str) -> tuple[int, str]:
        """Path -> (parent dir ino, last component); parent must be an
        existing directory."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise _Err(errno.EINVAL, "empty path")
        _, parent = self._resolve("/".join(parts[:-1]))
        if not parent["mode"] & S_IFDIR:
            raise _Err(errno.ENOTDIR, path)
        return parent["ino"], parts[-1]

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, conn, msg) -> None:
        if isinstance(msg, M.MClientReply):
            # reply to one of OUR slave requests to a peer MDS
            with self._inflight_lock:
                w = self._peer_waiters.pop(msg.tid, None)
            if w is not None:
                w["reply"] = msg
                w["event"].set()
            return
        if not isinstance(msg, M.MClientRequest):
            return
        try:
            out = self._handle(msg.op, msg.args, conn)
            conn.send_message(M.MClientReply(msg.tid, 0, out))
        except _Redirect as r:
            conn.send_message(M.MClientReply(
                msg.tid, -errno.ESTALE,
                {"redirect_rank": r.rank,
                 "redirect_addr": list(r.addr)}))
        except _Err as e:
            conn.send_message(M.MClientReply(msg.tid, -e.errno,
                                             {"error": str(e)}))
        except RadosError as e:
            conn.send_message(M.MClientReply(msg.tid, -e.errno,
                                             {"error": str(e)}))
        except Exception as e:  # noqa: BLE001 - daemon must not die
            conn.send_message(M.MClientReply(
                msg.tid, -errno.EIO, {"error": repr(e)}))

    PATH_OPS = frozenset({
        "open", "stat", "mkdir", "create", "readdir", "setattr",
        "unlink", "rmdir", "snap_create", "snap_rm", "snap_list",
        "snap_resolve", "export_dir"})

    def _handle(self, op: str, a: dict, conn=None) -> dict:
        if op in self.PATH_OPS or op == "rename":
            # subtree authority first (redirect to the owner), then the
            # migration freeze (EAGAIN: retry until authority settles).
            # Rename gates BOTH paths; a foreign SRC does not redirect —
            # the dst owner executes and removes the foreign dentry
            # through the src owner (peer_drm), so no rank ever mutates
            # a dirfrag it does not own.
            paths = ([a["dst"], a["src"]] if op == "rename"
                     else [a["path"]])
            if op == "export_dir":      # the drainer itself is not
                self._authority_gate(a["path"])          # counted
                self._frozen_gate(a["path"])
                self.ops_served += 1
                return self._handle_gated(op, a, conn)
            # register in-flight BEFORE the freeze check: an op that
            # passed the gate must already be visible to the export
            # drain loop, or the map could commit under its feet
            with self._inflight_lock:
                self._inflight += 1
            try:
                for p in paths:
                    self._authority_gate(p, allow_foreign=(
                        op == "rename" and p == a.get("src")))
                    self._frozen_gate(p)
                self.ops_served += 1
                return self._handle_gated(op, a, conn)
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
        return self._handle_gated(op, a, conn)

    def _handle_gated(self, op: str, a: dict, conn=None) -> dict:
        if op == "export_dir":
            # gated above: only the subtree's owner reaches here
            return self._handle_export_dir(a)
        if op == "peer_drm":
            return self._handle_peer_drm(a)
        if op == "mds_takeover":
            return self._handle_takeover(a)
        if op == "subtree_map":
            return {"map": self._load_subtrees(force=True)}
        if op == "mount":
            sess = a.get("client")
            if sess:
                with self._cap_lock:
                    self._sessions[sess] = conn
            with self._cap_lock:
                epoch = self._snap_epoch
            return {"block_size": self.block_size,
                    "data_pool": DATA_POOL, "root": ROOT_INO,
                    "snapc": self._fs_snapc(), "snap_epoch": epoch}
        if op == "open":
            return self._handle_open(a)
        if op == "snap_create":
            return self._handle_snap_create(a)
        if op == "snap_rm":
            return self._handle_snap_rm(a)
        if op == "snap_list":
            _, ent = self._resolve(a["path"])
            rows = self._snap_rows(ent["ino"])
            return {"snaps": sorted(rows)}
        if op == "snap_resolve":
            return self._handle_snap_resolve(a)
        if op == "cap_flush":
            return self._handle_cap_flush(a)
        if op == "cap_release":
            with self._cap_lock:
                holders = self._caps.get(a["ino"], {})
                holders.pop(a.get("client", ""), None)
                if not holders:
                    self._caps.pop(a["ino"], None)
            return {}
        if op == "stat":
            _, ent = self._resolve(a["path"])
            return {"ent": ent}
        if op == "mkdir":
            dino, name = self._split(a["path"])
            with self._dir_lock(dino):
                if self._dget(dino, name) is not None:
                    raise _Err(errno.EEXIST, a["path"])
                ino = self._alloc_ino()
                ent = {"ino": ino, "mode": S_IFDIR | 0o755, "size": 0,
                       "mtime": time.time()}
                ev = {"op": "mkdir", "dino": dino, "name": name,
                      "ent": ent}
                seq = self.mdlog.append(ev)
                try:
                    self.meta.execute(f"dir.{ino:x}", "rgw",
                                      "dir_init", b"")
                    self._dset(dino, name, ent)
                except Exception:
                    self._finish_event(seq, ev)
                    raise
                self.mdlog.mark_done(seq)
            return {"ino": ino}
        if op == "create":
            dino, name = self._split(a["path"])
            with self._dir_lock(dino):
                ent = self._dget(dino, name)
                if ent is not None:
                    if ent["mode"] & S_IFDIR:
                        raise _Err(errno.EISDIR, a["path"])
                    if a.get("excl"):
                        raise _Err(errno.EEXIST, a["path"])
                    return {"ent": ent}
                ino = self._alloc_ino()
                ent = {"ino": ino, "mode": S_IFREG | 0o644, "size": 0,
                       "mtime": time.time()}
                self._dset(dino, name, ent)
            return {"ent": ent}
        if op == "readdir":
            _, ent = self._resolve(a["path"])
            if not ent["mode"] & S_IFDIR:
                raise _Err(errno.ENOTDIR, a["path"])
            return {"entries": self._dlist(ent["ino"])}
        if op == "setattr":
            # client reports size/mtime after data writes (the reduced
            # form of cap recall; reference Server::handle_client_setattr)
            dino, name = self._split(a["path"])
            with self._dir_lock(dino):
                ent = self._dget(dino, name)
                if ent is None:
                    raise _Err(errno.ENOENT, a["path"])
                if not self._attr_apply(ent, a):
                    return {"ent": ent}
                self._dset(dino, name, ent)
            return {"ent": ent}
        if op == "unlink":
            dino, name = self._split(a["path"])
            with self._dir_lock(dino):
                ent = self._dget(dino, name)
                if ent is None:
                    raise _Err(errno.ENOENT, a["path"])
                if ent["mode"] & S_IFDIR:
                    raise _Err(errno.EISDIR, a["path"])
                ev = {"op": "unlink", "dino": dino, "name": name,
                      "ent": ent}
                seq = self.mdlog.append(ev)
                try:
                    self._drm(dino, name)
                except Exception:
                    self._finish_event(seq, ev)
                    raise
            self._purge_data(ent)
            self.mdlog.mark_done(seq)
            return {}
        if op == "rmdir":
            dino, name = self._split(a["path"])
            # lock BOTH the parent's stripe and the victim dir's own
            # stripe: the emptiness check must exclude a concurrent
            # create inside the victim.  The ino is read before
            # locking, so re-verify it under the locks (the dentry may
            # have been replaced) and retry with the fresh ino.
            for _ in range(8):
                ent = self._dget(dino, name)
                if ent is None:
                    raise _Err(errno.ENOENT, a["path"])
                with self._multi_lock(dino, ent["ino"]):
                    cur = self._dget(dino, name)
                    if cur is None:
                        raise _Err(errno.ENOENT, a["path"])
                    if cur["ino"] != ent["ino"]:
                        continue   # replaced meanwhile: retry
                    if not cur["mode"] & S_IFDIR:
                        raise _Err(errno.ENOTDIR, a["path"])
                    if self._dcount(cur["ino"]) > 0:
                        raise _Err(errno.ENOTEMPTY, a["path"])
                    ev = {"op": "rmdir", "dino": dino, "name": name,
                          "ino": cur["ino"]}
                    seq = self.mdlog.append(ev)
                    try:
                        self._drm(dino, name)
                        try:
                            self.meta.remove(f"dir.{cur['ino']:x}")
                        except RadosError:
                            pass
                    except Exception:
                        self._finish_event(seq, ev)
                        raise
                    self.mdlog.mark_done(seq)
                return {}
            raise _Err(errno.EAGAIN, a["path"])
        if op == "rename":
            src_owner = self._authority(a["src"])
            if src_owner not in (None, self.rank):
                return self._rename_cross(a, src_owner)
            sdino, sname = self._split(a["src"])
            ddino, dname = self._split(a["dst"])
            if (sdino, sname) == (ddino, dname):
                if self._dget(sdino, sname) is None:
                    raise _Err(errno.ENOENT, a["src"])
                return {}   # POSIX: rename to itself is a no-op
            replaced = None
            with self._multi_lock(sdino, ddino):
                ent = self._dget(sdino, sname)
                if ent is None:
                    raise _Err(errno.ENOENT, a["src"])
                existing = self._dget(ddino, dname)
                if existing is not None:
                    if existing["mode"] & S_IFDIR:
                        raise _Err(errno.EISDIR, a["dst"])
                    if existing["ino"] != ent["ino"]:
                        replaced = existing
                ev = {"op": "rename", "sdino": sdino, "sname": sname,
                      "ddino": ddino, "dname": dname, "ent": ent,
                      "replaced": replaced}
                seq = self.mdlog.append(ev)
                try:
                    self._dset(ddino, dname, ent)
                    self._drm(sdino, sname)
                except Exception:
                    self._finish_event(seq, ev)
                    raise
            if replaced is not None:
                # the displaced file's inode lost its last link: purge
                # its data like unlink would (reference purge queue)
                self._purge_data(replaced)
            self.mdlog.mark_done(seq)
            return {}
        raise _Err(errno.EOPNOTSUPP, op)

    # -- FS snapshots (reference SnapServer / .snap, reduced) ---------------

    def _snap_rows(self, dino: int) -> dict[str, dict]:
        """Registry rows for one directory: small (snapid/created),
        the manifest lives in its own object."""
        try:
            raw = self.meta.execute(
                SNAP_REGISTRY, "rgw", "dir_list",
                json.dumps({"prefix": f"{dino:x}/",
                            "max": 10000}).encode())
        except RadosError as e:
            if e.errno == errno.ENOENT:
                return {}        # registry never created: no snaps
            raise                # cluster fault != "no snapshots"
        out = json.loads(raw.decode())
        if out["truncated"]:
            raise RadosError(errno.EIO,
                             "snap registry exceeds one page")
        return {k.split("/", 1)[1]: m for k, m in out["entries"]}

    @staticmethod
    def _manifest_oid(dino: int, name: str) -> str:
        return f"snapmanifest.{dino:x}.{name}"

    def _collect_subtree(self, dino: int, rel: str = "") -> dict:
        """Eager namespace manifest: relpath -> entry, recursively."""
        manifest: dict[str, dict] = {}
        for name, ent in self._dlist(dino):
            path = f"{rel}{name}"
            manifest[path] = ent
            if ent["mode"] & S_IFDIR:
                manifest.update(
                    self._collect_subtree(ent["ino"], f"{path}/"))
        return manifest

    def _fs_snapc(self) -> list:
        """[seq, [ids desc]] across every live snapshot (one data pool
        -> one SnapContext, like the reference's global snap realm).
        Cached; snap_create/rm invalidate.  A registry READ FAULT must
        raise, never degrade to "no snapshots" — a purge under an
        empty snapc destroys snapshot data."""
        with self._cap_lock:
            if self._snapc_cache is not None:
                return list(self._snapc_cache)
            epoch_at_read = self._snap_epoch
        ids = []
        try:
            raw = self.meta.execute(
                SNAP_REGISTRY, "rgw", "dir_list",
                json.dumps({"max": 10000}).encode())
            out = json.loads(raw.decode())
            if out["truncated"]:
                # a snapc missing ids silently destroys those
                # snapshots on the next purge — refuse instead
                raise RadosError(errno.EIO,
                                 "snap registry exceeds one page")
            for _k, m in out["entries"]:
                ids.append(int(m["snapid"]))
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        ids.sort(reverse=True)
        snapc = [ids[0] if ids else 0, ids]
        with self._cap_lock:
            # a snap_create/rm racing this read has bumped the epoch:
            # its registry row may be missing from our list, and
            # caching it would pin a stale snapc until the NEXT
            # mutation — only cache what no mutation outran
            if self._snap_epoch == epoch_at_read:
                self._snapc_cache = list(snapc)
        return snapc

    def _snap_mutated(self) -> tuple[list, int]:
        """Invalidate + recompute the snapc and bump the epoch clients
        order their updates by; returns (snapc, epoch)."""
        with self._cap_lock:
            self._snapc_cache = None
            self._snap_epoch += 1
            epoch = self._snap_epoch
        snapc = self._fs_snapc()
        self._broadcast_snapc(snapc, epoch)
        return snapc, epoch

    def _broadcast_snapc(self, snapc: list, epoch: int) -> None:
        payload = json.dumps(snapc)
        with self._cap_lock:
            conns = list(self._sessions.values())
        for conn in conns:
            try:
                conn.send_message(
                    M.MClientCaps("snapc", 0, payload, epoch))
            except Exception:  # noqa: BLE001 - dead session
                pass

    def _handle_snap_create(self, a: dict) -> dict:
        _, ent = self._resolve(a["path"])
        if not ent["mode"] & S_IFDIR:
            raise _Err(errno.ENOTDIR, a["path"])
        dino = ent["ino"]
        if a["name"] in self._snap_rows(dino):
            raise _Err(errno.EEXIST, a["name"])
        snapid = self.data.selfmanaged_snap_create()
        manifest = self._collect_subtree(dino)
        # manifest first (its own object: registry rows stay tiny),
        # then the registry row that makes the snapshot visible
        self.meta.write_full(
            self._manifest_oid(dino, a["name"]),
            json.dumps(manifest, separators=(",", ":")).encode())
        self.meta.execute(SNAP_REGISTRY, "rgw", "dir_add", json.dumps({
            "key": f"{dino:x}/{a['name']}",
            "meta": {"snapid": snapid,
                     "created": time.time()}}).encode())
        snapc, epoch = self._snap_mutated()
        return {"snapid": snapid, "snapc": snapc, "snap_epoch": epoch}

    def _handle_snap_rm(self, a: dict) -> dict:
        _, ent = self._resolve(a["path"])
        rows = self._snap_rows(ent["ino"])
        row = rows.get(a["name"])
        if row is None:
            raise _Err(errno.ENOENT, a["name"])
        self.meta.execute(SNAP_REGISTRY, "rgw", "dir_rm", json.dumps({
            "key": f"{ent['ino']:x}/{a['name']}"}).encode())
        try:
            self.meta.remove(self._manifest_oid(ent["ino"], a["name"]))
        except RadosError:
            pass
        # let the OSD snap trimmer reclaim the clones
        try:
            self.data.selfmanaged_snap_remove(int(row["snapid"]))
        except RadosError:
            pass   # advisory; trim just won't run for this id yet
        snapc, epoch = self._snap_mutated()
        return {"snapc": snapc, "snap_epoch": epoch}

    def _handle_snap_resolve(self, a: dict) -> dict:
        """path/.snap/<name>/<rel> -> (ent at snap time, snapid).
        rel='' names the snapshotted dir itself; 'entries' lists one
        level of the manifest for readdir."""
        _, ent = self._resolve(a["path"])
        rows = self._snap_rows(ent["ino"])
        row = rows.get(a["name"])
        if row is None:
            raise _Err(errno.ENOENT, f".snap/{a['name']}")
        rel = a.get("rel", "").strip("/")
        manifest = json.loads(self.meta.read(
            self._manifest_oid(ent["ino"], a["name"]), 0).decode())
        if rel:
            target = manifest.get(rel)
            if target is None:
                raise _Err(errno.ENOENT, rel)
        else:
            target = {"ino": ent["ino"], "mode": S_IFDIR, "size": 0,
                      "mtime": row["created"]}
        out = {"ent": target, "snapid": int(row["snapid"])}
        if target["mode"] & S_IFDIR:
            pfx = f"{rel}/" if rel else ""
            out["entries"] = sorted(
                (p[len(pfx):], e) for p, e in manifest.items()
                if p.startswith(pfx) and "/" not in p[len(pfx):])
        return out

    # -- capabilities (reference Locker::issue_caps / revoke) ---------------

    def _handle_open(self, a: dict) -> dict:
        """Open with caps: create if asked, then grant "rwc" to a sole
        opener or shared "rw" (revoking other holders' cache cap
        first, waiting for their flush ack)."""
        sess = a.get("client", "")
        want = a.get("want", "r")
        dino, name = self._split(a["path"])
        with self._dir_lock(dino):
            ent = self._dget(dino, name)
            if ent is None:
                if not a.get("create"):
                    raise _Err(errno.ENOENT, a["path"])
                ino = self._alloc_ino()
                ent = {"ino": ino, "mode": S_IFREG | 0o644, "size": 0,
                       "mtime": time.time()}
                ev = {"op": "create", "dino": dino, "name": name,
                      "ent": ent}
                seq = self.mdlog.append(ev)
                try:
                    self._dset(dino, name, ent)
                except Exception:
                    self._finish_event(seq, ev)
                    raise
                self.mdlog.mark_done(seq)
            elif ent["mode"] & S_IFDIR:
                raise _Err(errno.EISDIR, a["path"])
            elif a.get("excl"):
                raise _Err(errno.EEXIST, a["path"])
        ino = ent["ino"]
        # grant outside the dir lock: revocation blocks on other
        # clients' acks
        to_revoke: list[tuple] = []
        with self._cap_lock:
            holders = self._caps.setdefault(ino, {})
            others = [s for s in holders if s != sess]
            grant = want + ("c" if not others else "")
            for s in others:
                if "c" in holders[s]:
                    # drop the cache right: holder must flush first
                    self._cap_seq += 1
                    to_revoke.append((s, holders[s].replace("c", ""),
                                      self._cap_seq))
            holders[sess] = grant
            self._cap_seq += 1
            grant_seq = self._cap_seq
        for s, newcaps, seq in to_revoke:
            self._revoke(s, ino, newcaps, seq)
        # re-read: the flush may have updated size/mtime.  A rename/
        # unlink racing in after the grant means the path no longer
        # names this inode — tell the opener rather than hand back a
        # stale pre-flush size
        ent = self._dget(dino, name)
        if ent is None or ent["ino"] != ino:
            with self._cap_lock:
                self._caps.get(ino, {}).pop(sess, None)
            raise _Err(errno.ENOENT, a["path"])
        return {"ent": ent, "caps": grant, "cap_seq": grant_seq}

    def _revoke(self, sess: str, ino: int, newcaps: str,
                seq: int, timeout: float = 10.0) -> None:
        with self._cap_lock:
            conn = self._sessions.get(sess)
        if conn is None:
            with self._cap_lock:
                self._caps.get(ino, {}).pop(sess, None)
            return
        ev = threading.Event()
        self._flush_waiters[(sess, ino, seq)] = ev
        try:
            conn.send_message(M.MClientCaps("revoke", ino, newcaps, seq))
        except Exception:  # noqa: BLE001 - dead session
            self._flush_waiters.pop((sess, ino, seq), None)
            with self._cap_lock:
                self._caps.get(ino, {}).pop(sess, None)
            return
        if not ev.wait(timeout):
            # unresponsive holder: drop its caps (reference session
            # autoclose on cap revoke timeout)
            with self._cap_lock:
                self._caps.get(ino, {}).pop(sess, None)
        self._flush_waiters.pop((sess, ino, seq), None)

    def _handle_cap_flush(self, a: dict) -> dict:
        """Holder's answer to a revoke (or a voluntary writeback):
        apply flushed attrs, record the reduced caps, wake the
        revoker."""
        if "path" in a and ("size" in a or "mtime" in a):
            try:
                dino, name = self._split(a["path"])
                with self._dir_lock(dino):
                    ent = self._dget(dino, name)
                    if ent is not None and ent["ino"] == a["ino"] and \
                            self._attr_apply(ent, a):
                        self._dset(dino, name, ent)
            except _Err:
                pass   # path raced away; the flush is advisory now
        sess = a.get("client", "")
        with self._cap_lock:
            if a.get("caps"):
                self._caps.setdefault(a["ino"], {})[sess] = a["caps"]
            else:
                self._caps.get(a["ino"], {}).pop(sess, None)
        ev = self._flush_waiters.get((sess, a["ino"], a.get("seq", 0)))
        if ev is not None:
            ev.set()
        return {}

    @staticmethod
    def _attr_apply(ent: dict, a: dict) -> bool:
        """Ordered attr update: each client stamps its setattr/cap_flush
        with a per-client monotonically increasing tick, and an update
        ordered BEFORE the entry's last update from the SAME client is
        dropped (a revoke-time flush racing that client's own later
        write-through).  Wall clocks are never compared across clients
        — different machines' clocks carry no ordering."""
        src = a.get("client")
        tick = a.get("tick")
        if src is not None and tick is not None:
            last = ent.get("attr_src")
            if last and last[0] == src and last[1] >= tick:
                return False
            ent["attr_src"] = [src, tick]
        for k in ("size", "mtime"):
            if k in a:
                ent[k] = a[k]
        return True

    # -- mdlog replay (reference MDLog::replay) ------------------------------

    def _apply_event(self, ev: dict) -> None:
        """Redo one journaled mutation; checks current state first so
        re-applying is idempotent."""
        op = ev["op"]
        if op in ("create", "mkdir"):
            if op == "mkdir":
                self.meta.execute(f"dir.{ev['ent']['ino']:x}",
                                  "rgw", "dir_init", b"")
            if self._dget(ev["dino"], ev["name"]) is None:
                self._dset(ev["dino"], ev["name"], ev["ent"])
        elif op == "unlink":
            cur = self._dget(ev["dino"], ev["name"])
            if cur is not None and cur["ino"] == ev["ent"]["ino"]:
                self._drm(ev["dino"], ev["name"])
            self._purge_data(ev["ent"])
        elif op == "rmdir":
            cur = self._dget(ev["dino"], ev["name"])
            if cur is not None and cur["ino"] == ev["ino"]:
                self._drm(ev["dino"], ev["name"])
            try:
                self.meta.remove(f"dir.{ev['ino']:x}")
            except RadosError:
                pass
        elif op == "rename":
            dst = self._dget(ev["ddino"], ev["dname"])
            if dst is None or dst["ino"] != ev["ent"]["ino"]:
                self._dset(ev["ddino"], ev["dname"], ev["ent"])
            src = self._dget(ev["sdino"], ev["sname"])
            if src is not None and src["ino"] == ev["ent"]["ino"]:
                self._drm(ev["sdino"], ev["sname"])
            if ev.get("replaced"):
                self._purge_data(ev["replaced"])
        elif op == "export":
            # the subtree-map write is the commit point: if it landed,
            # the export completed; if not, authority never moved and
            # there is nothing to roll back (the freeze dies with the
            # crashed process).  Either way the intent just retires.
            pass
        elif op == "rename_cross":
            dst = self._dget(ev["ddino"], ev["dname"])
            if dst is None or dst["ino"] != ev["ent"]["ino"]:
                self._dset(ev["ddino"], ev["dname"], ev["ent"])
            # finish the foreign-side removal: directly if we own the
            # src dirfrag by now (takeover), else through its owner
            cur = self._dget(ev["sdino"], ev["sname"])
            if cur is not None and cur["ino"] == ev["ent"]["ino"]:
                if getattr(self, "messenger", None) is None:
                    # boot-time replay (messenger not built yet):
                    # complete the ino-guarded removal directly
                    self._drm(ev["sdino"], ev["sname"])
                else:
                    try:
                        self._peer_request(
                            ev["src_owner"], "peer_drm", {
                                "dino": ev["sdino"],
                                "name": ev["sname"],
                                "ino": ev["ent"]["ino"]})
                    except _Err:
                        # peer dead/unknown: direct removal
                        self._drm(ev["sdino"], ev["sname"])
            if ev.get("replaced"):
                self._purge_data(ev["replaced"])

    def _replay_mdlog(self) -> None:
        for seq, ev in self.mdlog.pending():
            self._apply_event(ev)
            self.mdlog.mark_done(seq)

    def _finish_event(self, seq: int, ev: dict) -> None:
        """Error path after an intent was journaled: an intent must not
        linger while the MDS keeps serving — hours later a restart
        would replay it over NEWER state (clobbering a file created at
        dst since, or deleting a file the client was told still
        exists).  Drive the redo to completion NOW via the idempotent
        replay handler; only if that also fails does the intent stay
        pending for the (imminent) restart to finish."""
        self._apply_event(ev)
        self.mdlog.mark_done(seq)

    def _multi_lock(self, *inos: int):
        """Acquire the stripe locks of several inodes deadlock-free:
        ordered by STRIPE INDEX (two renames ordering by raw ino could
        take aliased stripes in opposite order), deduplicated."""
        import contextlib

        idxs = sorted({ino % len(self._locks) for ino in inos})
        locks = [self._locks[i] for i in idxs]

        @contextlib.contextmanager
        def _ctx():
            for lk in locks:
                lk.acquire()
            try:
                yield
            finally:
                for lk in reversed(locks):
                    lk.release()
        return _ctx()

    def _purge_data(self, ent: dict) -> None:
        """Remove a dead inode's data blocks (reference PurgeQueue).
        The removal carries the fs SnapContext: blocks referenced by a
        live snapshot are COW-preserved by the OSD (delete clones +
        snapdir), not destroyed."""
        snapc = self._fs_snapc()
        self.data.snapc = snapc if snapc[1] else None
        nblocks = -(-max(ent.get("size", 0), 1) // self.block_size)
        for b in range(nblocks):
            try:
                self.data.remove(data_oid(ent["ino"], b))
            except RadosError:
                pass


class _Redirect(Exception):
    """This rank is not the path's authority: bounce the client to
    the owner (reference MDS forward / auth hints)."""

    def __init__(self, rank: str, addr: tuple):
        super().__init__(f"redirect to mds {rank} at {addr}")
        self.rank = rank
        self.addr = addr


class _Err(Exception):
    def __init__(self, err: int, msg: str = ""):
        super().__init__(msg)
        self.errno = err
