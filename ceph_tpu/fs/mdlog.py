"""MDLog: the MDS metadata journal.

Re-expresses reference src/mds/MDLog.h + journal/ at the granularity
this MDS needs: every multi-step namespace mutation writes an INTENT
event to a per-MDS log object BEFORE touching the directory objects,
and marks it done after.  A crashed MDS replays pending events on
restart, completing (redo semantics) whatever half-applied mutation it
died inside — without the log, a rename could leave the file linked in
both directories or neither.

The log object lives in the metadata pool and uses omap: one row per
event, keyed by zero-padded sequence number (the role of the
reference's journal segments in the metadata pool); completion removes
the row (the reference expires whole segments — row-per-event is the
honest equivalent at this scale).  Events record REDO data: applying
one twice must be idempotent, which each replay handler guarantees by
checking current state first.
"""

from __future__ import annotations

import json
import threading


def _log_oid(rank) -> str:
    return f"mds_log.{rank}"


class MDLog:
    def __init__(self, meta_ioctx, rank="0"):
        self.io = meta_ioctx
        self.rank = rank
        self._seq = 0
        # MDS handlers run concurrently (per-connection dispatch
        # threads); an unsynchronized counter would hand two intents
        # the same row, one silently overwriting the other
        self._seq_lock = threading.Lock()
        # resume the sequence past any pending entries
        pending = self.pending()
        if pending:
            self._seq = max(seq for seq, _ in pending)

    def append(self, event: dict) -> int:
        """Durably record an intent; returns its seq for mark_done."""
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        self.io.omap_set(_log_oid(self.rank), {
            f"{seq:016d}".encode():
                json.dumps(event, separators=(",", ":")).encode()})
        return seq

    def mark_done(self, seq: int) -> None:
        self.io.omap_rm_keys(_log_oid(self.rank),
                             [f"{seq:016d}".encode()])

    def pending(self) -> list[tuple[int, dict]]:
        """Events whose mutation may be half-applied, in log order."""
        from ..rados.client import RadosError
        try:
            kv = self.io.omap_get_vals(_log_oid(self.rank))
        except RadosError:
            return []
        return sorted((int(k.decode()), json.loads(v.decode()))
                      for k, v in kv.items())
