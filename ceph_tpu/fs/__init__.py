"""CephFS-role POSIX-ish file service over RADOS.

Re-expresses the reference's file service shape (src/mds/ MDS daemon +
src/client/ libcephfs) at reduced scope: an MDS daemon owns the
namespace — directories are objects in a metadata pool whose entries
embed the child inodes (reference CDir dirfrags as omap objects with
inodes embedded in dentries) — while clients do file DATA I/O directly
against the data pool in fixed-size striped blocks (the reference's
file layout), talking to the MDS only for metadata.
"""

from .mds import MDSDaemon
from .client import CephFS, FSError

__all__ = ["MDSDaemon", "CephFS", "FSError"]
