"""Small shared utilities."""
