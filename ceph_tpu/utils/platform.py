"""JAX platform selection that survives this image's axon sitecustomize.

The container's sitecustomize force-selects an experimental `axon` TPU
platform via jax.config.update("jax_platforms", "axon,cpu"), which
overrides the JAX_PLATFORMS env var.  First contact with the TPU tunnel
can take minutes and may fail with UNAVAILABLE — and backend init is
blocking and uninterruptible in-process.  So tools that must always make
progress (bench.py, the benchmark CLI) probe the accelerator in a
*subprocess* with a timeout, then pin this process to the best backend
that actually works.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE_RESULT: str | None = None

PROBE_CODE = (
    "import jax\n"
    "d = jax.devices()\n"
    "print(d[0].platform)\n"
)


def probe_accelerator(timeout: float | None = None) -> bool:
    """True if the default (TPU) backend initializes within `timeout`s."""
    timeout = timeout or float(os.environ.get("CEPH_TPU_PROBE_TIMEOUT", "120"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_CODE],
            capture_output=True, timeout=timeout, text=True)
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def ensure_usable_backend(prefer_cpu: bool = False) -> str:
    """Pin jax to a working backend; returns its name ('axon'/'tpu'/'cpu').

    Must run before any jax backend initialization in this process.
    """
    global _PROBE_RESULT
    import jax

    if prefer_cpu:
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    if _PROBE_RESULT is None:
        platforms = os.environ.get("JAX_PLATFORMS", "")
        if "axon" in platforms or platforms in ("", "tpu"):
            _PROBE_RESULT = "accel" if probe_accelerator() else "cpu"
        else:
            _PROBE_RESULT = "accel"
    if _PROBE_RESULT == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    return jax.default_backend()
