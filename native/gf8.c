/* GF(2^8) region kernels — the CPU-best erasure-code data path.
 *
 * Native equivalent of the reference's vendored GF kernels (gf-complete
 * SSSE3 "split table" w=8 region multiply; ISA-L ec_encode_data,
 * reference src/erasure-code/isa/ErasureCodeIsa.cc:129): multiply a
 * memory region by a GF(2^8) constant and XOR-accumulate, vectorized
 * with PSHUFB nibble lookups when available.  Polynomial 0x11d, matching
 * ceph_tpu/ec/gf.py.
 *
 * API (ctypes-friendly):
 *   gf8_init()                                build log/exp + nibble tables
 *   gf8_mul_region_xor(c, src, dst, len)      dst ^= c * src
 *   gf8_encode(k, m, matrix, data, parity, len)
 *       matrix: m*k coefficients (row r = parity r), data/parity:
 *       arrays of pointers to chunk buffers of `len` bytes.
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

#if defined(__x86_64__)
#include <immintrin.h>
#include <cpuid.h>
#endif

#define GF_POLY 0x11d

static uint8_t gf_mul_table[256][256];
static uint8_t nib_lo[256][16];  /* c * x  for x in 0..15            */
static uint8_t nib_hi[256][16];  /* c * (x<<4) for x in 0..15        */
static int gf_ready = 0;

static uint8_t slow_mul(uint8_t a, uint8_t b) {
    uint16_t r = 0, aa = a;
    while (b) {
        if (b & 1) r ^= aa;
        aa <<= 1;
        if (aa & 0x100) aa ^= GF_POLY;
        b >>= 1;
    }
    return (uint8_t)r;
}

void gf8_init(void) {
    if (gf_ready) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            gf_mul_table[a][b] = slow_mul((uint8_t)a, (uint8_t)b);
    for (int c = 0; c < 256; c++)
        for (int x = 0; x < 16; x++) {
            nib_lo[c][x] = gf_mul_table[c][x];
            nib_hi[c][x] = gf_mul_table[c][x << 4];
        }
    gf_ready = 1;
}

#if defined(__x86_64__)
static int have_ssse3(void) {
    static int cached = -1;
    if (cached < 0) {
        unsigned eax, ebx, ecx, edx;
        cached = __get_cpuid(1, &eax, &ebx, &ecx, &edx) && (ecx & bit_SSSE3);
    }
    return cached;
}

__attribute__((target("avx2")))
static void mul_region_xor_avx2(uint8_t c, const uint8_t *src, uint8_t *dst,
                                size_t len) {
    __m256i lo = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i *)nib_lo[c]));
    __m256i hi = _mm256_broadcastsi128_si256(
        _mm_loadu_si128((const __m128i *)nib_hi[c]));
    __m256i mask = _mm256_set1_epi8(0x0f);
    size_t i = 0;
    for (; i + 32 <= len; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i *)(src + i));
        __m256i l = _mm256_and_si256(v, mask);
        __m256i h = _mm256_and_si256(_mm256_srli_epi64(v, 4), mask);
        __m256i p = _mm256_xor_si256(_mm256_shuffle_epi8(lo, l),
                                     _mm256_shuffle_epi8(hi, h));
        __m256i d = _mm256_loadu_si256((const __m256i *)(dst + i));
        _mm256_storeu_si256((__m256i *)(dst + i), _mm256_xor_si256(d, p));
    }
    for (; i < len; i++)
        dst[i] ^= gf_mul_table[c][src[i]];
}

static int have_avx2(void) {
    static int cached = -1;
    if (cached < 0) {
        unsigned eax, ebx, ecx, edx;
        cached = 0;
        if (__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx))
            cached = (ebx & bit_AVX2) != 0;
    }
    return cached;
}

__attribute__((target("ssse3")))
static void mul_region_xor_ssse3(uint8_t c, const uint8_t *src, uint8_t *dst,
                                 size_t len) {
    __m128i lo = _mm_loadu_si128((const __m128i *)nib_lo[c]);
    __m128i hi = _mm_loadu_si128((const __m128i *)nib_hi[c]);
    __m128i mask = _mm_set1_epi8(0x0f);
    size_t i = 0;
    for (; i + 16 <= len; i += 16) {
        __m128i v = _mm_loadu_si128((const __m128i *)(src + i));
        __m128i l = _mm_and_si128(v, mask);
        __m128i h = _mm_and_si128(_mm_srli_epi64(v, 4), mask);
        __m128i p = _mm_xor_si128(_mm_shuffle_epi8(lo, l),
                                  _mm_shuffle_epi8(hi, h));
        __m128i d = _mm_loadu_si128((const __m128i *)(dst + i));
        _mm_storeu_si128((__m128i *)(dst + i), _mm_xor_si128(d, p));
    }
    for (; i < len; i++)
        dst[i] ^= gf_mul_table[c][src[i]];
}
#endif

static void xor_region(const uint8_t *src, uint8_t *dst, size_t len) {
    size_t i = 0;
    for (; i + 8 <= len; i += 8) {
        uint64_t a, b;
        memcpy(&a, src + i, 8);
        memcpy(&b, dst + i, 8);
        b ^= a;
        memcpy(dst + i, &b, 8);
    }
    for (; i < len; i++)
        dst[i] ^= src[i];
}

void gf8_mul_region_xor(uint8_t c, const uint8_t *src, uint8_t *dst,
                        size_t len) {
    if (!gf_ready) gf8_init();
    if (c == 0) return;
    if (c == 1) { xor_region(src, dst, len); return; }
#if defined(__x86_64__)
    if (have_avx2()) { mul_region_xor_avx2(c, src, dst, len); return; }
    if (have_ssse3()) { mul_region_xor_ssse3(c, src, dst, len); return; }
#endif
    const uint8_t *t = gf_mul_table[c];
    for (size_t i = 0; i < len; i++)
        dst[i] ^= t[src[i]];
}

void gf8_encode(int k, int m, const uint8_t *matrix,
                const uint8_t **data, uint8_t **parity, size_t len) {
    if (!gf_ready) gf8_init();
    for (int r = 0; r < m; r++) {
        memset(parity[r], 0, len);
        for (int j = 0; j < k; j++)
            gf8_mul_region_xor(matrix[r * k + j], data[j], parity[r], len);
    }
}
