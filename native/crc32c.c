/* crc32c (Castagnoli) — hardware + software paths + combine.
 *
 * Native equivalent of the reference's checksum stack
 * (src/common/crc32c.cc dispatching to crc32c_intel_fast /
 * crc32c_aarch64 / sctp_crc32 software fallback, plus
 * ceph_crc32c_zeros-style combine helpers): same polynomial 0x1EDC6F41
 * (reflected 0x82F63B78), same init/xor conventions as
 * bufferlist::crc32c (src/include/buffer.h:1199).
 *
 * Build: cc -O3 -fPIC -shared (see Makefile); SSE4.2 path compiled in
 * when available and selected at runtime via cpuid.
 */

#include <stdint.h>
#include <stddef.h>

#if defined(__x86_64__) || defined(__i386__)
#include <cpuid.h>
#endif

#define POLY_REFLECTED 0x82F63B78u

/* ---------------- software: slice-by-8 ---------------- */

static uint32_t table[8][256];
static int table_ready = 0;

static void init_tables(void) {
    if (table_ready) return;
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int j = 0; j < 8; j++)
            c = (c & 1) ? (c >> 1) ^ POLY_REFLECTED : c >> 1;
        table[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = table[0][i];
        for (int s = 1; s < 8; s++) {
            c = (c >> 8) ^ table[0][c & 0xff];
            table[s][i] = c;
        }
    }
    table_ready = 1;
}

static uint32_t crc32c_sw(uint32_t crc, const uint8_t *buf, size_t len) {
    init_tables();
    while (len && ((uintptr_t)buf & 7)) {
        crc = (crc >> 8) ^ table[0][(crc ^ *buf++) & 0xff];
        len--;
    }
    while (len >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, buf, 8);
        v ^= crc;
        crc = table[7][v & 0xff] ^ table[6][(v >> 8) & 0xff] ^
              table[5][(v >> 16) & 0xff] ^ table[4][(v >> 24) & 0xff] ^
              table[3][(v >> 32) & 0xff] ^ table[2][(v >> 40) & 0xff] ^
              table[1][(v >> 48) & 0xff] ^ table[0][(v >> 56) & 0xff];
        buf += 8;
        len -= 8;
    }
    while (len--)
        crc = (crc >> 8) ^ table[0][(crc ^ *buf++) & 0xff];
    return crc;
}

/* ---------------- hardware: SSE4.2 crc32 instruction ---------------- */

#if defined(__x86_64__)
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t *buf, size_t len) {
    while (len && ((uintptr_t)buf & 7)) {
        crc = __builtin_ia32_crc32qi(crc, *buf++);
        len--;
    }
    uint64_t c = crc;
    while (len >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, buf, 8);
        c = __builtin_ia32_crc32di(c, v);
        buf += 8;
        len -= 8;
    }
    crc = (uint32_t)c;
    while (len--)
        crc = __builtin_ia32_crc32qi(crc, *buf++);
    return crc;
}

static int have_sse42(void) {
    static int cached = -1;
    if (cached < 0) {
        unsigned eax, ebx, ecx, edx;
        cached = __get_cpuid(1, &eax, &ebx, &ecx, &edx) && (ecx & bit_SSE4_2);
    }
    return cached;
}
#endif

uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t *buf, size_t len) {
#if defined(__x86_64__)
    if (have_sse42())
        return crc32c_hw(crc, buf, len);
#endif
    return crc32c_sw(crc, buf, len);
}

/* ---------------- combine: crc(A||B) from crc(A), crc(B), len(B) -----
 *
 * GF(2) matrix method (zlib-style): advancing a CRC over n zero bytes is
 * multiplication of the crc (as a GF(2) 32-vector) by M_zero^n; combine =
 * shift crc(A) over len(B) zeros then xor crc(B).  This is also exactly
 * what the reference's ceph_crc32c_zeros enables (extending a crc across
 * zero padding without touching memory).
 */

static uint32_t gf2_times(const uint32_t *mat, uint32_t vec) {
    uint32_t sum = 0;
    int i = 0;
    while (vec) {
        if (vec & 1) sum ^= mat[i];
        vec >>= 1;
        i++;
    }
    return sum;
}

static void gf2_square(uint32_t *sq, const uint32_t *mat) {
    for (int i = 0; i < 32; i++)
        sq[i] = gf2_times(mat, mat[i]);
}

uint32_t ceph_tpu_crc32c_zeros(uint32_t crc, uint64_t len) {
    if (len == 0) return crc;
    uint32_t even[32], odd[32];
    /* odd = matrix for one zero *bit*: shift right, feed poly */
    odd[0] = POLY_REFLECTED;
    for (int i = 1; i < 32; i++)
        odd[i] = 1u << (i - 1);
    gf2_square(even, odd);   /* 2 bits */
    gf2_square(odd, even);   /* 4 bits */
    /* now loop: apply for each set bit of byte-length, matrices advance
     * 8*2^k bits = 2^(k+3) */
    uint64_t n = len;
    /* start with matrix for 1 byte (8 bits): square 4-bit matrix once */
    gf2_square(even, odd);   /* 8 bits = 1 byte */
    uint32_t (*cur)[32] = &even, (*next)[32] = &odd;
    do {
        if (n & 1)
            crc = gf2_times(*cur, crc);
        n >>= 1;
        if (!n) break;
        gf2_square(*next, *cur);
        uint32_t (*t)[32] = cur; cur = next; next = t;
    } while (1);
    return crc;
}

uint32_t ceph_tpu_crc32c_combine(uint32_t crc_a, uint32_t crc_b,
                                 uint64_t len_b) {
    return ceph_tpu_crc32c_zeros(crc_a, len_b) ^ crc_b;
}
