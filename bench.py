#!/usr/bin/env python
"""Headline benchmark: EC encode GB/s, k=8 m=3, 1 MiB stripes (vs CPU).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

value       = jax-plugin (TPU when available) encode throughput, input
              GB/s over 1 MiB objects split k=8 + m=3 parity, batched.
vs_baseline = value / best-CPU-plugin throughput measured on this host —
              the stand-in for the reference's ISA-L single-socket number
              (the reference publishes no absolute numbers; BASELINE.md).

Mirrors the canonical invocation of the reference benchmark
(src/erasure-code/isa/README: `-p isa -P k=8 -P m=3 -S 1048576 -i 1000`).
"""

import json
import os
import sys
import time

import numpy as np

K, M, SIZE = 8, 3, 1 << 20


def time_encode_cpu(codec, chunks, min_iters=5, min_time=2.0):
    codec.encode_chunks(chunks)
    t0 = time.perf_counter()
    iters = 0
    while iters < min_iters or time.perf_counter() - t0 < min_time:
        codec.encode_chunks(chunks)
        iters += 1
    return iters * SIZE / (time.perf_counter() - t0)


def time_encode_jax(codec, chunks, batch=32, min_time=2.0):
    import jax
    import jax.numpy as jnp
    stripes = jnp.asarray(np.stack([chunks] * batch))
    out = codec.encode_stripes(stripes)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < min_time:
        out = codec.encode_stripes(stripes)
        iters += 1
    jax.block_until_ready(out)
    elapsed = time.perf_counter() - t0
    return iters * batch * SIZE / elapsed


def best_jax_throughput(codec, chunks):
    """Sweep batch sizes; device-resident batches amortize launch cost
    differently on TPU vs the CPU fallback."""
    import jax
    batches = (8, 32, 128) if jax.default_backend() != "cpu" else (8,)
    best = 0.0
    for b in batches:
        try:
            best = max(best, time_encode_jax(codec, chunks, batch=b))
        except Exception as e:  # noqa: BLE001 - e.g. OOM at large batch
            print(f"# batch {b} failed: {e}", file=sys.stderr)
    return best


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.utils.platform import ensure_usable_backend

    backend = ensure_usable_backend()
    print(f"# backend: {backend}", file=sys.stderr)

    reg = ErasureCodePluginRegistry.instance()
    prof = {"k": str(K), "m": str(M), "technique": "cauchy"}
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, SIZE, dtype=np.uint8).tobytes()

    jax_codec = reg.factory("jax", dict(prof))
    chunks = jax_codec.encode_prepare(payload)

    # CPU denominator: best available CPU plugin (native C if built).
    cpu_best = 0.0
    for plugin, p in (("isa", {"k": str(K), "m": str(M)}),
                      ("jerasure", {"k": str(K), "m": str(M),
                                    "technique": "cauchy_good"})):
        try:
            c = reg.factory(plugin, p)
            cpu_best = max(cpu_best, time_encode_cpu(c, chunks))
        except Exception as e:  # noqa: BLE001
            print(f"# cpu plugin {plugin} failed: {e}", file=sys.stderr)

    value = best_jax_throughput(jax_codec, chunks)

    out = {
        "metric": "ec_encode_k8_m3_1MiB",
        "value": round(value / 1e9, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / cpu_best, 3) if cpu_best else None,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
