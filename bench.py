#!/usr/bin/env python
"""Headline benchmark: FUSED EC encode+crc GB/s, k=8 m=3, 1 MiB stripes.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N,
   "value_min": ..., "value_max": ..., "n_passes": ..., "cpu_abs_GBps": ...}

value       = MEDIAN of n_passes independent slope measurements of the
              jax-plugin FUSED parity+crc throughput (the point every
              production write actually pays: the OSD always updates
              HashInfo, reference ECUtil.cc:172), input GB/s over
              1 MiB objects split k=8 + m=3 parity, batched and
              device-resident.  Bare encode (the old headline) rides
              along as ec_encode_k8_m3_1MiB_GBps with its own spread —
              the fused:bare gap IS the crc tax the overlapped kernel
              attacks.  On a CPU-only run the fused TPU kernel cannot
              execute, so the row falls back to the bare-encode
              headline (marked via "headline").  Passes are SPACED
              over minutes: the shared axon tunnel swings single
              samples 2-3x by hour-of-day, so one sample is weather,
              the median of spaced samples is climate.  value_min/max
              publish the observed spread so two runs can be compared
              honestly.  fused_point/fused_path record the autotuned
              operating point (tile, wb, extraction variant, combine
              depth — ops/autotune.py) and the kernel path the passes
              ran through, so a round-over-round move is attributable
              to kernel vs tuning changes.
vs_baseline = value / the PINNED CPU denominator: best CPU plugin,
              fixed iteration count, median of repeats — recorded
              absolutely so the ratio's movement can always be
              attributed to the numerator or denominator.  For the
              fused headline the denominator is cpu_crc_abs_GBps (CPU
              encode + the host crc pass over every shard — the
              reference's two-pass cost); bare-encode fallback rows
              keep cpu_abs_GBps.

Measurement method (each pass): the encode is chained through a
`lax.fori_loop` (each iteration's input depends on the previous
parity) and timed as the difference between a 150-iteration and a
50-iteration dispatch.  This defeats both async-dispatch
undercounting and any runtime-level elision/caching of repeated
identical computations (observed over the axon tunnel: timing the
same buffer repeatedly reports impossible, above-roofline numbers),
and cancels the dispatch/tunnel latency.

Knobs (env): BENCH_PASSES (default 5 on TPU, 1 on CPU),
BENCH_SPACING_S (default 25 on TPU, 0 on CPU).

Mirrors the canonical invocation of the reference benchmark
(src/erasure-code/isa/README: `-p isa -P k=8 -P m=3 -S 1048576 -i 1000`).
"""

import json
import os
import sys
import time

import numpy as np

K, M, SIZE = 8, 3, 1 << 20
BATCH = 32                      # 1 MiB objects per device batch
ITERS_LO, ITERS_HI = 50, 150
CPU_ITERS = 2000                # fixed work per CPU timing repeat
CPU_REPEATS = 5

# Roofline sanity gate: v5e HBM is ~820 GB/s, so no honest input-GB/s
# sample can exceed ~1 TB/s.  Samples above this are timing elisions
# (observed over the axon tunnel: BENCH_r04 published a 16,448,278 GB/s
# value_max when the fori-loop chaining defense silently failed on 2 of
# 5 passes) and are rejected, re-drawing from the retry budget.
ROOFLINE_BPS = 1e12


def time_encode_cpu(codec, chunks, iters=CPU_ITERS, repeats=CPU_REPEATS):
    """Pinned denominator: FIXED iteration count, median of repeats.
    The old adaptive-duration loop let the measured rate pick its own
    sample size, which moved the published ratio between rounds on
    denominator noise alone (r02 6.26 vs r03 4.10 GB/s, same code)."""
    codec.encode_chunks(chunks)          # warm
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            codec.encode_chunks(chunks)
        rates.append(iters * SIZE / (time.perf_counter() - t0))
    rates.sort()
    return rates[len(rates) // 2]


CPU_CRC_ITERS = 300             # fixed work per CPU fused-repeat


def time_encode_crc_cpu(codec, chunks, iters=CPU_CRC_ITERS,
                        repeats=CPU_REPEATS):
    """Pinned denominator of the FUSED headline: the reference's
    two-pass cost — plugin encode, then a full host crc walk over
    every data+parity shard (ECUtil.cc HashInfo::append) — at fixed
    iteration count, median of repeats.  Uses the native crc path when
    built; the numpy table fallback is ~1000x slower, so iterations
    drop to keep the (rarely exercised) fallback run bounded."""
    from ceph_tpu.common import crc32c as _crc
    from ceph_tpu.common import native
    if native.load() is None:
        iters = max(iters // 100, 1)
    k = chunks.shape[0]
    n = codec.get_chunk_count()
    seeds = [0xFFFFFFFF] * n
    par = codec.encode_chunks(chunks)    # warm
    # two row-wise passes (data, then parity) — the reference walks
    # existing buffers; a concatenate memcpy inside the timed loop
    # would deflate the denominator by its copy cost
    _crc.crc32c_rows(chunks, seeds[:k])
    _crc.crc32c_rows(par, seeds[k:])
    rates = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            par = codec.encode_chunks(chunks)
            _crc.crc32c_rows(chunks, seeds[:k])
            _crc.crc32c_rows(par, seeds[k:])
        rates.append(iters * SIZE / (time.perf_counter() - t0))
    rates.sort()
    return rates[len(rates) // 2]


def _slope_time(step, x0, rows, iters_lo=ITERS_LO, iters_hi=ITERS_HI,
                batch=BATCH):
    """Chained fori_loop slope timing: `step(x)` returns (rows, W); each
    iteration XORs the result back into x's first `rows` rows so no two
    iterations are identical (defeats runtime elision/caching — see
    module docstring).  Returns bytes/sec over batch*SIZE per iter.

    On TPU, several independent slope estimates are taken from ONE
    compiled pair of harnesses and the MEDIAN is reported: shared-
    tunnel contention swings single estimates 2-3x, and a transient
    non-positive pass is tolerated as long as any pass lands."""
    import jax
    from jax import lax

    def make(iters):
        @jax.jit
        def f(x):
            def body(i, x):
                r = step(x)
                return x.at[:rows, :].set(x[:rows, :] ^ r)
            return lax.fori_loop(0, iters, body, x)
        return f

    f_lo, f_hi = make(iters_lo), make(iters_hi)
    # Every repetition gets a DISTINCT input: repeating an identical
    # call can be served from the runtime/tunnel cache, making min()
    # pick an elided (impossibly fast) run — observed as hi < lo.
    reps = 4
    variants = [jax.block_until_ready(x0 ^ (i + 1)) for i in range(reps)]
    jax.block_until_ready(f_lo(x0))                  # compile
    jax.block_until_ready(f_hi(x0))
    passes = 3 if jax.default_backend() != "cpu" else 1
    dts = []
    last = (0.0, 0.0)
    for _ in range(passes + 2):                      # +2 retry budget
        lo, hi = [], []
        for i in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f_lo(variants[i]))
            lo.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(f_hi(variants[i]))
            hi.append(time.perf_counter() - t0)
        dt = (min(hi) - min(lo)) / (iters_hi - iters_lo)
        last = (min(lo), min(hi))
        # accept only physically possible slopes (see ROOFLINE_BPS)
        if dt > 0 and batch * SIZE / dt < ROOFLINE_BPS:
            dts.append(dt)
            if len(dts) >= passes:
                break
        # fresh inputs for the next pass (or jitter retry)
        variants = [jax.block_until_ready(v ^ 0x5A) for v in variants]
    if not dts:
        raise RuntimeError(
            f"non-positive slope: timing elided or too noisy "
            f"(lo={last[0]:.4f}s hi={last[1]:.4f}s)")
    dts.sort()
    return batch * SIZE / dts[len(dts) // 2]


def time_encode_jax(codec):
    """Slope-timed device-resident encode (see _slope_time)."""
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() != "cpu"
    batch = BATCH if on_tpu else 2   # CPU smoke: small + fast
    k, m, n = K, M, SIZE // K
    rng = np.random.default_rng(0)
    flat = rng.integers(0, 256, (k, batch * n), dtype=np.uint8)

    if on_tpu:
        x0 = jnp.asarray(flat.view(np.int32))        # word-packed path
        enc = codec.encode_words
        lo, hi = ITERS_LO, ITERS_HI
    else:
        x0 = jnp.asarray(flat)
        enc = codec.encode_chunks_device
        lo, hi = 3, 9
    enc(x0)                                          # build bitmats eagerly
    return _slope_time(enc, x0, m, iters_lo=lo, iters_hi=hi,
                       batch=batch)


def time_encode_crc_jax(codec):
    """Slope-timed fused parity+crc (the north-star configuration: the
    OSD write path always pays the checksum, reference ECUtil.cc:172,
    so the headline should include it).  TPU only — times the
    device-side-combine fused launch (ops/bitsliced.py
    gf_encode_with_crc_w32_fold: one L per shard per dispatch) at the
    AUTOTUNED operating point (ops/autotune.py; the first call on a
    fresh device pays the cached sweep, outside the timed region).
    The crc output feeds the fori_loop chain so neither output can be
    elided, and samples pass the same roofline gate as the headline
    (_slope_time rejects above-1TB/s elisions)."""
    import jax
    import jax.numpy as jnp

    k, m, n = K, M, SIZE // K
    rng = np.random.default_rng(2)
    flat = rng.integers(0, 256, (k, BATCH * n), dtype=np.uint8)
    x0 = jnp.asarray(flat.view(np.int32))
    codec.fused_point()              # resolve autotune before timing

    def step(x):
        par, crc = codec.encode_words_with_crc(x)
        return par ^ jnp.sum(crc)
    step(x0)                                         # build matrices
    return _slope_time(step, x0, m)


def time_decode_jax(codec, erasures):
    """Slope-timed device-resident decode.

    Mirrors the reference decode benchmark (`-w decode -e 1/2/3`,
    src/erasure-code/isa/README): erase the first `erasures` chunks,
    reconstruct them from k survivors.  Input accounting matches the
    reference (bytes of the original object per iteration).
    """
    import jax
    import jax.numpy as jnp

    on_tpu = jax.default_backend() != "cpu"
    batch = BATCH if on_tpu else 2
    k, m, n = K, M, SIZE // K
    erased = tuple(range(erasures))
    survivors = tuple(i for i in range(k + m) if i not in erased)[:k]
    rng = np.random.default_rng(1)
    flat = rng.integers(0, 256, (k, batch * n), dtype=np.uint8)

    if on_tpu:
        x0 = jnp.asarray(flat.view(np.int32))
        def dec(x):
            return codec.decode_words(x, survivors, erased)
        lo, hi = 50, 350
    else:
        x0 = jnp.asarray(flat)
        def dec(x):
            return codec.decode_chunks_device(x, survivors, erased)
        lo, hi = 3, 9
    dec(x0)                                          # build decode plan
    # decode iterations are cheap relative to tunnel jitter: a wider
    # iteration spread keeps the slope's relative noise down
    return _slope_time(dec, x0, erasures, iters_lo=lo, iters_hi=hi,
                       batch=batch)


# -- end-to-end write pipeline + deep scrub (ISSUE 3) ------------------------
#
# The kernel slope numbers above measure the codec alone; these two
# measure the PATH the paper is about: client writes through the
# ECBackend 3-stage pipeline into a (mem)store, and deep scrub
# re-verifying every shard.  The pipeline metric is an A/B —
# dispatch-ahead (depth-2 window, drain N+1 assembles while drain N
# computes on device, completion in submit order) vs sync (every drain
# materialized before the next op) — on the same sizes, so the
# published speedup isolates exactly the host-sync stalls the
# dispatch-ahead work removes.

PIPE_DEPTH = 2


def _pipeline_backend(chunk: int):
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.osd.ec_backend import ECBackend, LocalShardBackend
    from ceph_tpu.osd.ec_util import StripeInfo
    from ceph_tpu.osd.types import pg_t
    from ceph_tpu.store import MemStore
    reg = ErasureCodePluginRegistry.instance()
    codec = reg.factory("jax", {"k": str(K), "m": str(M),
                                "technique": "cauchy"})
    sinfo = StripeInfo(stripe_width=K * chunk, chunk_size=chunk)
    store = MemStore()
    store.mount()
    shards = LocalShardBackend(store, pg_t(1, 0), K + M)
    return ECBackend(codec, sinfo, shards, dispatch_depth=PIPE_DEPTH)


def _pipeline_payloads(nobj: int, objsize: int):
    rng = np.random.default_rng(11)
    return [rng.integers(0, 256, objsize, dtype=np.uint8)
            for _ in range(nobj)]


def time_write_pipeline(pipelined: bool, nobj: int, objsize: int,
                        chunk: int, payloads=None,
                        tracker=None, per_op=None) -> float:
    """Wall-clock input bytes/sec of `nobj` object writes through the
    full ECBackend path (plan -> assemble -> fused encode+crc launch ->
    hinfo fold -> per-shard sub-writes on MemStore), every op its own
    drain.  pipelined=True opens the dispatch-ahead window (flush at
    exit included in the timing); False materializes each drain before
    the next submit — the A/B contrast.  tracker: an OpTracker makes
    every op a TrackedOp with the full stage timeline (the always-on
    daemon configuration; the tracked-vs-untracked delta is the
    tracking overhead guard, docs/TRACING.md).  per_op: called with
    the op index before each submit — the ledger-overhead A/B injects
    the OSD write path's control-plane ledger touches here."""
    import contextlib
    from ceph_tpu.osd.ec_transaction import PGTransaction
    from ceph_tpu.osd.types import eversion_t, hobject_t
    backend = _pipeline_backend(chunk)
    payloads = payloads or _pipeline_payloads(nobj, objsize)
    acked = []
    ctx = backend.pipeline() if pipelined else contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx:
        for i, payload in enumerate(payloads):
            if per_op is not None:
                per_op(i)
            txn = PGTransaction()
            txn.write(hobject_t(pool=1, name=f"pipe{i}"), 0, payload)
            top = tracker.create("osd_op", f"pipe{i}") \
                if tracker is not None else None
            if top is not None:
                backend.submit_transaction(
                    txn, eversion_t(1, i + 1),
                    lambda t=top: (acked.append(1),
                                   tracker.unregister(t, 0)),
                    top=top)
            else:
                backend.submit_transaction(txn, eversion_t(1, i + 1),
                                           lambda: acked.append(1))
    dt = time.perf_counter() - t0
    if len(acked) != nobj:
        raise RuntimeError(f"pipeline bench: {len(acked)}/{nobj} acked")
    return nobj * objsize / dt


def time_tracking_overhead(nobj: int, objsize: int, chunk: int,
                           payloads, reps: int = 3
                           ) -> tuple[float, float, float]:
    """Tracked-vs-untracked A/B on the pipelined write path: `reps`
    interleaved runs each, best-of rates compared (best-of damps
    scheduler noise far better than medians at these run lengths).
    Returns (tracked_best, untracked_best, noise_pct) where noise_pct
    is the untracked spread — the measurement's own noise floor, which
    the smoke guard adds to its threshold so the assertion tests the
    tracker, not the scheduler."""
    from ceph_tpu.common.tracked_op import OpTracker
    untracked, tracked = [], []
    for _ in range(reps):
        untracked.append(time_write_pipeline(True, nobj, objsize,
                                             chunk, payloads))
        tracked.append(time_write_pipeline(
            True, nobj, objsize, chunk, payloads,
            tracker=OpTracker(complaint_time=30.0)))
    noise = (max(untracked) - min(untracked)) / max(untracked) * 100.0
    return max(tracked), max(untracked), noise


def time_profiler_overhead(nobj: int, objsize: int, chunk: int,
                           payloads, reps: int = 3
                           ) -> tuple[float, float, float]:
    """Flight-recorder on-vs-off A/B on the pipelined write path
    (mirrors time_tracking_overhead, PR 4's gate): the profiler
    records once per LAUNCH, so the always-on ledger must be as free
    as tracking is.  Returns (on_best, off_best, noise_pct of the
    off config)."""
    from ceph_tpu.ops.profiler import device_profiler
    prof = device_profiler()
    was = prof.enabled
    on, off = [], []
    try:
        for _ in range(reps):
            prof.enabled = False
            off.append(time_write_pipeline(True, nobj, objsize,
                                           chunk, payloads))
            prof.enabled = True
            on.append(time_write_pipeline(True, nobj, objsize,
                                          chunk, payloads))
    finally:
        prof.enabled = was
    noise = (max(off) - min(off)) / max(off) * 100.0
    return max(on), max(off), noise


def measure_profiler_overhead(reps: int = 3) -> tuple[float, float]:
    """(overhead_pct, noise_pct) of the flight recorder at smoke
    sizes — standalone so the --smoke gate can re-measure on a
    failing single shot (the box-wander retry rule the 64pg gate
    uses; a REAL per-launch regression fails every attempt)."""
    nobj, objsize, chunk = 6, 1 << 16, 1024
    payloads = _pipeline_payloads(nobj, objsize)
    time_write_pipeline(True, 2, objsize, chunk, payloads[:2])
    on, off, noise = time_profiler_overhead(nobj, objsize, chunk,
                                            payloads, reps=reps)
    return round((1.0 - on / off) * 100.0, 2), round(noise, 2)


def time_ledger_overhead(nobj: int, objsize: int, chunk: int,
                         payloads, reps: int = 3
                         ) -> tuple[float, float, float]:
    """Control-plane ledger on-vs-off A/B on the pipelined write path
    (ISSUE 19, mirrors time_profiler_overhead): per op the callback
    replays exactly the ledger touches the OSD write path pays — the
    enabled gate plus a degraded-ack count every op, a transition and
    a timed stage at recovery cadence — with the SAME callback wired
    into both configs so the A/B isolates the ledger's cost, not the
    callback's.  Returns (on_best, off_best, noise_pct of off)."""
    from ceph_tpu.osd.pg_ledger import PGLedger
    from ceph_tpu.osd.types import pg_t
    led = PGLedger("pg_ledger.bench", ring=64)
    pgid = pg_t(1, 0)

    def per_op(i: int) -> None:
        # the daemon's submit-path gate (osd/daemon.py): one enabled
        # check, then the degraded-ack count
        if led.enabled:
            led.degraded_ack(pgid)
        if i % 8 == 0:
            # recovery-cadence touches: transition + timed stage
            led.transition(pgid, "recovering" if i & 8 else "clean")
            with led.stage(pgid, "scan"):
                pass

    on, off = [], []
    for _ in range(reps):
        led.enabled = False
        off.append(time_write_pipeline(True, nobj, objsize, chunk,
                                       payloads, per_op=per_op))
        led.enabled = True
        on.append(time_write_pipeline(True, nobj, objsize, chunk,
                                      payloads, per_op=per_op))
    noise = (max(off) - min(off)) / max(off) * 100.0
    return max(on), max(off), noise


def measure_ledger_overhead(reps: int = 3) -> tuple[float, float]:
    """(overhead_pct, noise_pct) of the control-plane ledger at smoke
    sizes — standalone so the --smoke gate can re-measure on a failing
    single shot (the same box-wander retry rule as the profiler
    gate)."""
    nobj, objsize, chunk = 6, 1 << 16, 1024
    payloads = _pipeline_payloads(nobj, objsize)
    time_write_pipeline(True, 2, objsize, chunk, payloads[:2])
    on, off, noise = time_ledger_overhead(nobj, objsize, chunk,
                                          payloads, reps=reps)
    return round((1.0 - on / off) * 100.0, 2), round(noise, 2)


def time_msgr_overhead(nobj: int, objsize: int, chunk: int,
                       payloads, reps: int = 3
                       ) -> tuple[float, float, float]:
    """Wire-plane ledger on-vs-off A/B on the pipelined write path
    (ISSUE 20, mirrors time_ledger_overhead): per op the callback
    replays exactly the messenger-seam touches a data-path op pays —
    the enabled gate, a note_send + note_recv (per-peer/per-type
    counter bumps), and a dispatch_submit/run/done timing triple at
    dispatch cadence — with the SAME callback wired into both configs
    so the A/B isolates the ledger's cost, not the callback's.
    Returns (on_best, off_best, noise_pct of off)."""
    from ceph_tpu.msg.msgr_ledger import MsgrLedger
    led = MsgrLedger(enabled=True)
    stats = led.register_messenger("bench.cli")

    def per_op(i: int) -> None:
        # the messenger's send/recv gates (msg/messenger.py): one
        # enabled check each, then the per-peer accounting
        if led.enabled:
            stats.note_send("osd.0", "MOSDOp", 4096, i & 3)
            stats.note_recv("osd.0", "MOSDOpReply", 128)
        if i % 4 == 0:
            t_sub = led.dispatch_submit() if led.enabled else None
            if t_sub is not None:
                t_run = led.dispatch_run(t_sub)
                led.dispatch_done(t_run)

    on, off = [], []
    for _ in range(reps):
        led.enabled = False
        off.append(time_write_pipeline(True, nobj, objsize, chunk,
                                       payloads, per_op=per_op))
        led.enabled = True
        on.append(time_write_pipeline(True, nobj, objsize, chunk,
                                      payloads, per_op=per_op))
    noise = (max(off) - min(off)) / max(off) * 100.0
    return max(on), max(off), noise


def measure_msgr_overhead(reps: int = 3) -> tuple[float, float]:
    """(overhead_pct, noise_pct) of the wire-plane ledger at smoke
    sizes — standalone so the --smoke gate can re-measure on a failing
    single shot (the same box-wander retry rule as the profiler
    gate)."""
    nobj, objsize, chunk = 6, 1 << 16, 1024
    payloads = _pipeline_payloads(nobj, objsize)
    time_write_pipeline(True, 2, objsize, chunk, payloads[:2])
    on, off, noise = time_msgr_overhead(nobj, objsize, chunk,
                                        payloads, reps=reps)
    return round((1.0 - on / off) * 100.0, 2), round(noise, 2)


def ledger_block() -> dict:
    """The `launch_ledger` provenance block every bench row embeds
    (BENCH_r06+ rows are self-attributing): what the device plane
    actually did — launches, runs/launch, compile seconds, device-ms
    percentiles — plus the jax/jaxlib/device identity it did it on."""
    from ceph_tpu.ops.profiler import device_profiler
    prof = device_profiler()
    block = prof.bench_summary()
    ledger = prof.compile_ledger()
    block["compile_worst"] = ledger["buckets"][:3]
    return block


def time_tail_latency(nobj: int, objsize: int, chunk: int,
                      payloads) -> dict:
    """Per-stage p99 tail latency of the pipelined EC write path
    (ISSUE 9): every op tracked, stage intervals land in latency
    histograms (common/perf_counters.py), and the percentile pipeline
    turns them into per-stage p99s — so a tail regression names the
    stage (queue wait, encode launch vs materialize, sub-write ack,
    commit), not just "writes got slower".  Returns
    {"ec_write_p99_ms": end-to-end op p99,
     "ec_write_stage_p99_ms": {stage: p99_ms}}."""
    from ceph_tpu.common.perf_counters import PerfCountersBuilder
    from ceph_tpu.common.tracked_op import OpTracker
    perf = PerfCountersBuilder("optracker.bench").create_perf_counters()
    tracker = OpTracker(complaint_time=30.0, perf=perf)
    time_write_pipeline(True, nobj, objsize, chunk, payloads,
                        tracker=tracker)
    lat = perf.dump_latencies()
    stages = {}
    total_p99 = None
    for key, row in lat.items():
        p99 = row.get("p99")
        if p99 is None:
            continue
        if key == "lat_total_osd_op":
            total_p99 = round(p99 * 1e3, 4)
        elif key.startswith("lat_"):
            stages[key[len("lat_"):]] = round(p99 * 1e3, 4)
    return {"ec_write_p99_ms": total_p99,
            "ec_write_stage_p99_ms": stages}


def time_deep_scrub(nobj: int, objsize: int, chunk: int,
                    use_device: bool) -> tuple[float, dict]:
    """Shard bytes verified per second by a deep scrub of an EC
    k=8,m=3 PG (all k+m shards of every object read via batched
    fan-outs and crc32c'd — on device in one launch per chunk, or the
    host fallback).  Returns (bytes/sec, meta)."""
    from ceph_tpu.osd import scrub as scrub_mod
    from ceph_tpu.osd.ec_transaction import PGTransaction
    from ceph_tpu.osd.types import eversion_t, hobject_t
    backend = _pipeline_backend(chunk)
    payloads = _pipeline_payloads(nobj, objsize)
    oids = []
    with backend.pipeline():
        for i, payload in enumerate(payloads):
            oid = hobject_t(pool=1, name=f"scrub{i}")
            oids.append(oid)
            txn = PGTransaction()
            txn.write(oid, 0, payload)
            backend.submit_transaction(txn, eversion_t(1, i + 1),
                                       lambda: None)
    t0 = time.perf_counter()
    res = scrub_mod.scrub_pg(backend, oids, deep=True,
                             use_device=use_device)
    dt = time.perf_counter() - t0
    if not res.clean:
        raise RuntimeError(f"deep scrub found {len(res.errors)} errors "
                           f"on freshly written objects")
    shard_bytes = res.device_bytes + res.host_bytes
    if not shard_bytes:
        raise RuntimeError("deep scrub verified zero bytes")
    return shard_bytes / dt, {"device_bytes": res.device_bytes,
                              "host_bytes": res.host_bytes}


def bench_end_to_end(on_tpu: bool, passes: int, spacing: float) -> dict:
    """The ISSUE-3 metrics: pipelined-vs-sync write A/B + deep scrub."""
    if on_tpu:
        nobj, objsize, chunk = 16, 8 << 20, 16384   # 1 MiB shard runs
    else:
        nobj, objsize, chunk = 6, 1 << 16, 1024     # CPU smoke sizes
    payloads = _pipeline_payloads(nobj, objsize)
    # warm the jit caches (kernel + combine shapes) outside timing
    time_write_pipeline(True, 2, objsize, chunk, payloads[:2])
    out = {}
    pipe, sync = [], []
    reps = min(passes, 3) if on_tpu else 1
    for i in range(reps):
        if i and spacing:
            time.sleep(spacing)
        pipe.append(time_write_pipeline(True, nobj, objsize, chunk,
                                        payloads))
        sync.append(time_write_pipeline(False, nobj, objsize, chunk,
                                        payloads))
        print(f"# write pipeline pass {i + 1}/{reps}: "
              f"pipelined {pipe[-1] / 1e9:.2f} GB/s, "
              f"sync {sync[-1] / 1e9:.2f} GB/s", file=sys.stderr)
    pipe.sort()
    sync.sort()
    pipe_med = pipe[len(pipe) // 2]
    sync_med = sync[len(sync) // 2]
    out["ec_write_pipeline_k8_m3_GBps"] = round(pipe_med / 1e9, 3)
    out["ec_write_pipeline_sync_GBps"] = round(sync_med / 1e9, 3)
    out["ec_write_pipeline_speedup"] = round(pipe_med / sync_med, 3)
    # many-PG continuous batching (ISSUE 12, docs/PIPELINE.md "Host
    # launch queue"): the same total op count written through 64 PGs
    # sharing one per-host launch queue vs through 1 PG on the same
    # harness — aggregate GB/s must survive PG fan-out (gated in
    # --smoke within EC_64PG_MIN_FRAC of the 1-PG point), and the
    # queue's counters must prove multi-PG runs coalesced into shared
    # launches
    from ceph_tpu.tools.load_harness import run_ec_pg_sweep
    npg = int(os.environ.get("BENCH_PGS", "64"))
    mp_objs = 2 * npg
    mp_size = (2 << 20) if on_tpu else (1 << 16)
    # one measurement methodology for the fan-out claim: delegate to
    # the tier-1 sweep harness (warm passes at the MEASURED shapes,
    # best PAIRED pass per fan-out — see run_ec_pg_sweep); min_frac=0
    # because the gate lives in --smoke, not here
    sweep = run_ec_pg_sweep(pg_counts=(1, npg), total_objs=mp_objs,
                            objsize=mp_size, chunk=chunk, min_frac=0.0)
    out["ec_write_pipeline_64pg_GBps"] = sweep["agg_GBps"][str(npg)]
    out["ec_write_pipeline_64pg_base_GBps"] = sweep["agg_GBps"]["1"]
    out["ec_write_pipeline_64pg_frac"] = sweep["degradation_frac"]
    out["ec_write_pipeline_64pg_n"] = npg
    out["ec_host_queue_launches"] = sweep["launches"]
    out["ec_host_queue_runs_per_launch"] = sweep["runs_per_launch"]
    out["ec_host_queue_cross_pg_launches"] = sweep["cross_pg_launches"]
    out["ec_host_queue_occupancy_pct"] = sweep["occupancy_pct"]
    rate, meta = time_deep_scrub(nobj, objsize, chunk,
                                 use_device=on_tpu)
    out["ec_deep_scrub_GBps"] = round(rate / 1e9, 3)
    out["ec_deep_scrub_device_bytes"] = meta["device_bytes"]
    out["ec_deep_scrub_host_bytes"] = meta["host_bytes"]
    # always-on op tracking overhead (ISSUE 4 guard: must stay under
    # TRACK_OVERHEAD_MAX_PCT + the measured noise floor; asserted in
    # --smoke so a hot-path regression fails tier-1)
    t_best, u_best, noise = time_tracking_overhead(
        nobj, objsize, chunk, payloads, reps=3)
    out["ec_write_pipeline_tracked_GBps"] = round(t_best / 1e9, 3)
    out["ec_write_tracking_overhead_pct"] = round(
        (1.0 - t_best / u_best) * 100.0, 2)
    out["ec_write_tracking_noise_pct"] = round(noise, 2)
    # tail latency: per-stage p99 on the pipelined write path
    # (ISSUE 9 — throughput medians hide exactly what this shows)
    out.update(time_tail_latency(nobj, objsize, chunk, payloads))
    # QoS isolation: the deterministic virtual-time mClock experiment
    # (tools/load_harness.py) — greedy tenant vs reserved victim;
    # qos_isolation_ratio is gated in --smoke, no_qos_ratio is the
    # single-FIFO contrast that proves the scheduler is doing it
    from ceph_tpu.tools.load_harness import run_qos_isolation_sim
    qos = run_qos_isolation_sim("tenant")
    out["qos_isolation_ratio"] = qos["qos_isolation_ratio"]
    out["qos_no_qos_ratio"] = qos["no_qos_ratio"]
    out["qos_victim_p99_ms"] = qos["victim_qos_p99_ms"]
    out["qos_victim_alone_p99_ms"] = qos["victim_alone_p99_ms"]
    # flight-recorder overhead (ISSUE 15, mirrors PR 4's tracking
    # gate) + the launch-ledger provenance block: every row carries
    # its own device-plane explanation (launches, runs/launch,
    # compile seconds, device-ms percentiles, jax/device identity)
    p_on, p_off, p_noise = time_profiler_overhead(
        nobj, objsize, chunk, payloads, reps=3)
    out["ec_write_profiler_overhead_pct"] = round(
        (1.0 - p_on / p_off) * 100.0, 2)
    out["ec_write_profiler_noise_pct"] = round(p_noise, 2)
    # control-plane ledger overhead (ISSUE 19, same gate shape): the
    # per-PG state ledger rides the OSD write path's degraded-ack
    # check, so its on-vs-off cost is guarded like the other recorders
    l_on, l_off, l_noise = time_ledger_overhead(
        nobj, objsize, chunk, payloads, reps=3)
    out["ec_write_ledger_overhead_pct"] = round(
        (1.0 - l_on / l_off) * 100.0, 2)
    out["ec_write_ledger_noise_pct"] = round(l_noise, 2)
    # wire-plane ledger overhead (ISSUE 20, same gate shape): the
    # messenger ledger rides every send/recv/dispatch, so its
    # on-vs-off cost is guarded like the other two recorders
    m_on, m_off, m_noise = time_msgr_overhead(
        nobj, objsize, chunk, payloads, reps=3)
    out["ec_write_msgr_overhead_pct"] = round(
        (1.0 - m_on / m_off) * 100.0, 2)
    out["ec_write_msgr_noise_pct"] = round(m_noise, 2)
    out["launch_ledger"] = ledger_block()
    return out


# -- multichip mesh bench (ISSUE 10, docs/MULTICHIP.md) ----------------------
#
# The aggregate-GB/s numbers the MULTICHIP artifacts were missing:
# encode, encode+crc (what a mesh drain actually pays: sharded parity
# contraction + the vectorized host crc fold), and repair — each as a
# mesh vs single-chip A/B on the same host-resident inputs, so the
# published speedup isolates exactly what the collective program buys
# (or costs, on a virtual CPU mesh where the collectives are memcpys
# and the win is only correctness coverage).  Repair is measured the
# way the OSD now runs it: a BATCH of objects missing the same shards,
# one decode_flat_batch launch on the mesh vs the per-object
# decode_chunks loop the single-chip plane pays (reference accounting:
# original-object bytes per pass, like `-w decode`).

def _wall_rate(fn, nbytes: int, iters: int) -> float:
    """Wall-clock host-to-host bytes/sec: warm once, then time `iters`
    calls.  Both sides of every multichip A/B go through this so the
    comparison includes the real staging/transfer cost a drain pays."""
    fn()                                             # warm/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    dt = time.perf_counter() - t0
    if dt <= 0:
        raise RuntimeError("multichip bench: timer elided")
    return iters * nbytes / dt


def measure_multichip(jax_codec, dcodec, on_tpu: bool,
                      quick: bool = True) -> dict:
    """Mesh vs single-chip A/B on prebuilt codecs; returns the metric
    dict (all rates in GB/s of input bytes).  quick = CPU smoke sizes."""
    from ceph_tpu.common import crc32c as _crc

    k, m = dcodec.k, dcodec.m
    n = k + m
    if on_tpu and not quick:
        width, iters, nobj = 1 << 20, 8, 8
    else:
        width, iters, nobj = 1 << 15, 3, 4
    # byte width must satisfy the mesh quantum (per-device lanes)
    q = dcodec._quantum()
    width = max(q, width - width % q)
    rng = np.random.default_rng(5)
    flat = rng.integers(0, 256, (k, width), dtype=np.uint8)
    out: dict = {"phases": {}}

    # correctness gate first: mesh parity must be bit-identical to the
    # single-chip plane before any of its rates mean anything
    par_mesh = np.asarray(dcodec.encode_flat(flat))
    par_single = np.asarray(jax_codec.encode_chunks(flat))
    out["phases"]["encode_parity"] = bool(
        np.array_equal(par_mesh, par_single))

    nbytes = k * width
    out["mc_encode_mesh_GBps"] = round(_wall_rate(
        lambda: dcodec.encode_flat(flat), nbytes, iters) / 1e9, 3)
    out["mc_encode_single_GBps"] = round(_wall_rate(
        lambda: np.asarray(jax_codec.encode_chunks(flat)),
        nbytes, iters) / 1e9, 3)

    # encode+crc: the drain configuration (parity + per-shard crc32c)
    seeds = [0xFFFFFFFF] * n

    def mesh_encode_crc():
        par = np.asarray(dcodec.encode_flat(flat))
        return _crc.crc32c_rows(np.concatenate([flat, par]), seeds)

    def single_encode_crc():
        if hasattr(jax_codec, "encode_extents_with_crc_submit"):
            h = jax_codec.encode_extents_with_crc_submit([flat])
            par, l, tail, body = \
                jax_codec.encode_extents_with_crc_finalize(h)[0]
            return jax_codec.fold_extent_crcs(l, tail, seeds, body)
        par = np.asarray(jax_codec.encode_chunks(flat))
        return _crc.crc32c_rows(np.concatenate([flat, par]), seeds)

    crc_mesh = mesh_encode_crc()
    crc_single = single_encode_crc()
    out["phases"]["crc_parity"] = bool(list(crc_mesh) ==
                                       list(crc_single))
    out["mc_encode_crc_mesh_GBps"] = round(_wall_rate(
        mesh_encode_crc, nbytes, iters) / 1e9, 3)
    out["mc_encode_crc_single_GBps"] = round(_wall_rate(
        single_encode_crc, nbytes, iters) / 1e9, 3)

    # repair storm: `nobj` distinct objects all missing the same 3
    # shards — one batched mesh launch vs the per-object loop
    erased = (0, k - 1, k + 1)
    survivors = tuple(s for s in range(n) if s not in erased)[:k]
    objs = []
    for i in range(nobj):
        d = np.bitwise_xor(flat, np.uint8((i * 37 + 1) % 256))
        p = np.asarray(jax_codec.encode_chunks(d))
        objs.append(np.concatenate([d, p]))
    avail_list = [o[list(survivors)] for o in objs]

    def mesh_repair():
        return dcodec.decode_flat_batch(avail_list, survivors, erased)

    def single_repair():
        res = []
        for o in objs:
            dense = o.copy()
            for e in erased:
                dense[e] = 0
            res.append(jax_codec.decode_chunks(dense, list(erased)))
        return res

    reb_mesh = mesh_repair()
    reb_single = single_repair()
    ok = True
    for i, o in enumerate(objs):
        for j, e in enumerate(erased):
            ok = ok and np.array_equal(reb_mesh[i][j], o[e]) and \
                np.array_equal(reb_single[i][e], o[e])
    out["phases"]["repair_parity"] = bool(ok)
    repair_bytes = nobj * k * width       # original-object accounting
    out["mc_repair_mesh_GBps"] = round(_wall_rate(
        mesh_repair, repair_bytes, iters) / 1e9, 3)
    out["mc_repair_single_GBps"] = round(_wall_rate(
        single_repair, repair_bytes, iters) / 1e9, 3)
    out["mc_repair_batch_objects"] = nobj

    # CLAY repair storm (docs/REPAIR.md): the coupled-layer single-
    # failure repair lowered to one batched GF matmul — the mesh
    # collective vs the host plane-solver on identical repair-plane
    # inputs, bit-parity gated against the encoded original.  Helper
    # bytes (d helpers x 1/q chunk) are published beside the k-shard
    # full-read cost so the bandwidth claim stays falsifiable.
    out.update(measure_clay_repair(dcodec, k, m, on_tpu and not quick,
                                   phases=out["phases"]))

    for a, b, key in (("mc_encode_mesh_GBps", "mc_encode_single_GBps",
                       "mc_encode_speedup"),
                      ("mc_repair_mesh_GBps", "mc_repair_single_GBps",
                       "mc_repair_speedup"),
                      ("clay_repair_GBps", "clay_repair_host_GBps",
                       "clay_repair_speedup")):
        out[key] = round(out[a] / out[b], 3) if out.get(b) else None
    return out


def measure_clay_repair(dcodec, k: int, m: int, big: bool,
                        phases: dict | None = None) -> dict:
    """clay_repair_GBps: a storm of `nobj` objects that each lost the
    same chunk of a CLAY (k, m, d=k+m-1) pool, rebuilt from repair-
    plane reads only.  A/B: ONE mesh collective launch over the
    batched repair plan (`clay_repair_batch`) vs the per-object host
    plane-solver (`repair()`), both bit-parity-gated against the
    encoded originals.  Accounting matches mc_repair: original-object
    bytes per pass."""
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.parallel.mesh import ClayRepairPlan
    clay = ErasureCodePluginRegistry.instance().factory(
        "clay", {"k": str(k), "m": str(m)})      # d = k+m-1
    n = k + m
    sub = clay.get_sub_chunk_count()
    sub_size = 2048 if big else 128
    chunk = sub * sub_size
    nobj = 8 if big else 3
    iters = 6 if big else 3
    lost = 2                                     # a data shard
    plan = ClayRepairPlan.build(clay, lost)
    planes = clay.repair_planes(lost)
    rng = np.random.default_rng(17)
    rows_list, helpers_list, originals = [], [], []
    for i in range(nobj):
        payload = rng.integers(0, 256, k * chunk,
                               dtype=np.uint8).tobytes()
        enc = clay.encode(set(range(n)), payload)
        helpers = {ch: np.asarray(enc[ch]).reshape(sub, sub_size)[planes]
                   for ch in plan.helper_ids}
        helpers_list.append(helpers)
        rows_list.append(clay.repair_rows(lost, helpers))
        originals.append(np.asarray(enc[lost]))

    def mesh_clay():
        return dcodec.clay_repair_batch(plan, rows_list)

    def host_clay():
        return [clay.repair(lost, h, sub_size) for h in helpers_list]

    reb_mesh = mesh_clay()
    reb_host = host_clay()
    ok = True
    for i in range(nobj):
        ok = ok and np.array_equal(
            np.asarray(reb_mesh[i]).reshape(-1), originals[i])
        ok = ok and np.array_equal(reb_host[i], originals[i])
    if phases is not None:
        phases["clay_repair_parity"] = bool(ok)
    nbytes = nobj * k * chunk                    # original-object bytes
    out = {
        "clay_repair_GBps": round(_wall_rate(
            mesh_clay, nbytes, iters) / 1e9, 3),
        "clay_repair_host_GBps": round(_wall_rate(
            host_clay, nbytes, iters) / 1e9, 3),
        "clay_repair_batch_objects": nobj,
        "clay_sub_chunks": sub,
        "clay_d": clay.d,
        # the bandwidth claim, falsifiable: plane reads vs k full chunks
        "clay_helper_bytes_per_obj": clay.d * len(planes) * sub_size,
        "clay_full_read_bytes_per_obj": k * chunk,
    }
    return out


def run_multichip() -> int:
    """`bench.py --multichip`: build the host mesh through the
    MeshService deployment path and publish the aggregate mesh-vs-
    single-chip A/B as ONE JSON line (the MULTICHIP artifact row).
    CPU meshes get their virtual devices via XLA_FLAGS before jax
    initializes; returns nonzero when any phase or rate is bad, so
    scripts/tier1.sh can gate on it."""
    n_req = int(os.environ.get("MULTICHIP_DEVICES", "8"))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_req}"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ceph_tpu.utils.platform import ensure_usable_backend
    backend = ensure_usable_backend(
        prefer_cpu=os.environ.get("JAX_PLATFORMS") == "cpu")
    import jax
    on_tpu = jax.default_backend() != "cpu"
    have = len(jax.devices())
    out = {"metric": "ec_multichip", "unit": "GB/s",
           "backend": backend, "n_devices": min(n_req, have)}
    if have < 2:
        out["skipped"] = True
        out["error"] = f"only {have} device(s) visible"
        print(json.dumps(out))
        return 1
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.parallel.service import MeshService
    jax_codec = ErasureCodePluginRegistry.instance().factory(
        "jax", {"k": str(K), "m": str(M), "technique": "cauchy"})
    try:
        # the fused operating point rides every published row so
        # mesh-vs-single moves stay attributable to tuning changes
        out["fused_point"] = jax_codec.fused_point()
    except Exception:  # noqa: BLE001
        pass
    try:
        svc = MeshService.configure(min(n_req, have))
        dcodec = svc.acquire(K, M, technique="cauchy",
                             matrix=jax_codec.matrix)
    except Exception as e:  # noqa: BLE001 — MeshError et al.
        out["skipped"] = True
        out["error"] = f"mesh service: {e}"
        print(json.dumps(out))
        return 1
    out["mesh"] = {"shard": dcodec.n_shard, "data": dcodec.n_data}
    try:
        out.update(measure_multichip(jax_codec, dcodec, on_tpu,
                                     quick=not on_tpu))
    except Exception as e:  # noqa: BLE001
        out["error"] = f"multichip bench: {e}"
        print(json.dumps(out))
        return 1
    # device-plane provenance (ISSUE 15): the mesh row carries its
    # own launch/compile ledger like the end-to-end rows
    out["launch_ledger"] = ledger_block()
    print(json.dumps(out))
    bad = [p for p, ok in out["phases"].items() if not ok]
    bad += [key for key in ("mc_encode_mesh_GBps",
                            "mc_encode_crc_mesh_GBps",
                            "mc_encode_crc_single_GBps",
                            "mc_repair_mesh_GBps",
                            "mc_encode_single_GBps",
                            "mc_repair_single_GBps",
                            "clay_repair_GBps",
                            "clay_repair_host_GBps")
            if not isinstance(out.get(key), (int, float))
            or out[key] <= 0]
    # the CLAY bandwidth claim itself is a gate: plane reads must
    # undercut the RS k-shard full read
    if not (0 < out.get("clay_helper_bytes_per_obj", 0) <
            out.get("clay_full_read_bytes_per_obj", 0)):
        bad.append("clay_helper_bytes_per_obj")
    if bad:
        print(f"# multichip FAILED: {bad}", file=sys.stderr)
        return 1
    return 0


SMOKE_KEYS = ("ec_write_pipeline_k8_m3_GBps",
              "ec_write_pipeline_sync_GBps",
              "ec_write_pipeline_speedup",
              "ec_write_pipeline_tracked_GBps",
              "ec_write_pipeline_64pg_GBps",
              "ec_write_pipeline_64pg_base_GBps",
              "ec_deep_scrub_GBps")


def check_fused_kernel_smoke(out: dict) -> str | None:
    """--smoke gate (ISSUE 11): the fused metric must come from the
    hier kernel family — specifically the overlapped ACCUMULATOR
    kernel at an autotune-style operating point — not the XLA
    fallback.  On this CPU gate the kernel runs through the Pallas
    interpreter (the same kernel body and scalar-prefetch grid the
    TPU compiles), and its parity + crc are checked byte-exact against
    the host oracles, tail-free (the accumulator's L must cover the
    run's every byte).  Returns an error string, or None when the
    hier path produced the metric."""
    import jax.numpy as jnp

    from ceph_tpu.common import crc32c as _crc
    from ceph_tpu.ec import gf
    from ceph_tpu.ops import bitsliced as bs
    from ceph_tpu.ops import crc32c_linear as cl
    k, m = 4, 2
    tile, wb = 4096, 128
    mat = gf.cauchy_rs_matrix(k, m)[k:]
    bitmat = jnp.asarray(bs.interleave_bitmatrix(mat), dtype=jnp.int8)
    bitmat32 = jnp.asarray(bs._w32_bitmat(mat), dtype=jnp.int8)
    rng = np.random.default_rng(23)
    runs = [rng.integers(0, 256, (k, tile + 513), dtype=np.uint8)]
    handle = bs.gf_encode_extents_with_crc_submit(
        bitmat, bitmat32, runs, m, use_w32=True, force_xla=False,
        interpret=True, tile=tile, wb=wb, extract="wide",
        combine="kernel")
    out["ec_fused_path"] = handle.get("path")
    if handle.get("path") != "hier_acc":
        return (f"fused metric not produced by the hier accumulator "
                f"kernel (path={handle.get('path')!r})")
    [(par, l, tail, body)] = \
        bs.gf_encode_extents_with_crc_finalize(handle)
    if body != runs[0].shape[1] or tail.shape[1] != 0:
        return (f"accumulator L does not cover the run "
                f"(body={body}, tail={tail.shape[1]})")
    if not np.array_equal(np.asarray(par), gf.gf_matvec(mat, runs[0])):
        return "hier accumulator parity diverged from gf_matvec"
    allsh = np.concatenate([runs[0], np.asarray(par)], axis=0)
    for s in range(k + m):
        got = cl.fold_run_crc(int(l[s]), body, 0xFFFFFFFF)
        if got != _crc.crc32c(allsh[s].tobytes(), 0xFFFFFFFF):
            return f"hier accumulator crc diverged on shard {s}"
    return None


def check_clay_repair_smoke(out: dict) -> str | None:
    """--smoke gate (docs/REPAIR.md): the CLAY repair lowering must be
    bit-exact at both deployed geometries — the batched device plan
    (jitted XLA bit-sliced matmul) vs the host plane-solver vs the
    full-decode oracle — and the plane-read helper bytes must undercut
    the RS k-shard baseline.  Returns an error string, or None."""
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.parallel.mesh import ClayRepairPlan
    reg = ErasureCodePluginRegistry.instance()
    rng = np.random.default_rng(29)
    for k, m in ((4, 2), (8, 3)):
        clay = reg.factory("clay", {"k": str(k), "m": str(m)})
        n = k + m
        sub = clay.get_sub_chunk_count()
        sub_size = 16
        payload = rng.integers(0, 256, k * sub * sub_size,
                               dtype=np.uint8).tobytes()
        enc = clay.encode(set(range(n)), payload)
        dense = np.stack([np.asarray(enc[i]) for i in range(n)])
        lost = 1
        erased = dense.copy()
        erased[lost] = 0
        full = clay.decode_chunks(erased, [lost])[lost]
        if not np.array_equal(full, dense[lost]):
            return f"clay full decode diverged at k={k},m={m}"
        plan = ClayRepairPlan.build(clay, lost)
        planes = clay.repair_planes(lost)
        helpers = {ch: dense[ch].reshape(sub, sub_size)[planes]
                   for ch in plan.helper_ids}
        rows = clay.repair_rows(lost, helpers)
        host = clay.repair(lost, helpers, sub_size)
        dev = plan.apply_device(rows).reshape(-1)
        if not np.array_equal(host, full):
            return f"clay repair() != full decode at k={k},m={m}"
        if not np.array_equal(dev, full):
            return (f"clay device plan != host plane-solver at "
                    f"k={k},m={m}")
        helper_bytes = clay.d * len(planes) * sub_size
        if helper_bytes >= k * sub * sub_size:
            return (f"clay helper bytes {helper_bytes} not below the "
                    f"k-shard baseline {k * sub * sub_size}")
        out[f"clay_helper_frac_k{k}m{m}"] = round(
            helper_bytes / (k * sub * sub_size), 3)
    out["clay_repair_parity"] = True
    return None


def check_degraded_read_smoke(out: dict) -> str | None:
    """--smoke gate (docs/REPAIR.md): k=8,m=3 client reads during a
    shard-loss storm — a data shard down, background rebuild running
    concurrently — must ALL complete via reconstruct-on-read served by
    the batched decode path (perf counter + launch-queue decode
    launches asserted), zero loss, p99 published as
    degraded_read_p99_ms."""
    from ceph_tpu.common.perf_counters import percentiles_from_samples
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.osd.ec_backend import ECBackend, LocalShardBackend
    from ceph_tpu.osd.ec_transaction import PGTransaction
    from ceph_tpu.osd.ec_util import StripeInfo
    from ceph_tpu.osd.types import eversion_t, hobject_t, pg_t
    from ceph_tpu.parallel.launch_queue import ECLaunchQueue
    from ceph_tpu.store import MemStore
    import threading

    class DegradedShards(LocalShardBackend):
        down: set = set()

        def sub_read(self, shard, oid, off, length, on_done):
            if shard in self.down:
                on_done(shard, None)
                return
            super().sub_read(shard, oid, off, length, on_done)

    K_, M_, CH = 8, 3, 1024
    reg = ErasureCodePluginRegistry.instance()
    codec = reg.factory("jax", {"k": str(K_), "m": str(M_),
                                "technique": "cauchy"})
    store = MemStore()
    store.mount()
    shards = DegradedShards(store, pg_t(1, 0), K_ + M_)
    queue = ECLaunchQueue(window_us=500.0)
    try:
        be = ECBackend(codec, StripeInfo(K_ * CH, CH), shards,
                       launch_queue=queue, read_timeout=5.0)
        rng = np.random.default_rng(31)
        nobj = 8
        payloads = {}
        acked = []
        for i in range(nobj):
            oid = hobject_t(pool=1, name=f"dr{i}")
            p = rng.integers(0, 256, K_ * CH * 2, dtype=np.uint8)
            payloads[oid] = p
            txn = PGTransaction()
            txn.write(oid, 0, p)
            be.submit_transaction(txn, eversion_t(1, i + 1),
                                  lambda: acked.append(1))
        if len(acked) != nobj:
            return f"degraded-read smoke: {len(acked)}/{nobj} acked"
        shards.down = {2}                    # lose a data shard
        # the storm: background rebuild of every object runs while the
        # client reads land (pushes go nowhere — the point is the
        # concurrent decode load, not the store writes)
        def rebuild():
            be.recover_shards_batch(
                [(oid, [2]) for oid in payloads],
                lambda _oid: (lambda s, d, h: None))
        storm = threading.Thread(target=rebuild, daemon=True)
        storm.start()
        be.read(next(iter(payloads)))        # warm the decode plan
        samples = []
        bad = 0
        for _pass in range(2):
            for oid, p in payloads.items():
                t0 = time.perf_counter()
                got = be.read(oid)
                samples.append(time.perf_counter() - t0)
                if not np.array_equal(got, p):
                    bad += 1
        storm.join(timeout=30)
        pcts = percentiles_from_samples(samples, [(0.99, "p99"),
                                                  (0.5, "p50")])
        out["degraded_read_p99_ms"] = round(pcts.get("p99", 0.0) * 1e3,
                                            3)
        out["degraded_read_p50_ms"] = round(pcts.get("p50", 0.0) * 1e3,
                                            3)
        out["degraded_read_reads"] = len(samples)
        out["degraded_read_zero_loss"] = bad == 0
        d = be.perf.dump()
        out["degraded_read_reconstructs"] = int(
            d.get("ec_reconstruct_reads", 0))
        out["degraded_read_decode_launches"] = \
            queue.status()["decode_launches"]
        if bad:
            return f"{bad} degraded reads returned wrong bytes"
        if d.get("ec_reconstruct_reads", 0) < len(samples):
            return ("degraded reads not served by reconstruct-on-read "
                    f"({d.get('ec_reconstruct_reads')}/{len(samples)})")
        if queue.status()["decode_launches"] < 1:
            return "reconstruct-on-read bypassed the batched decode path"
        p99_max = float(os.environ.get("DEGRADED_READ_P99_MAX_MS",
                                       "2000.0"))
        if not out["degraded_read_p99_ms"] or \
                out["degraded_read_p99_ms"] > p99_max:
            return (f"degraded_read_p99_ms="
                    f"{out['degraded_read_p99_ms']} > {p99_max}")
        return None
    finally:
        queue.close()


def check_compile_storm_smoke(out: dict) -> str | None:
    """--smoke gate (ISSUE 15, docs/TRACING.md "Device plane"): an
    injected slow compile on a live 4-OSD cluster must surface
    EVERYWHERE the flight recorder promises — the mon's COMPILE_STORM
    health warning (profiler -> pgstats compile report -> health
    check), and a slow-op dump whose blame names the first-compiled
    bucket and whose timeline carries the launch id.  The injection
    (osd_ec_inject_compile_stall) sleeps inside the submit of every
    first-seen jit bucket: a real compile stall's exact shape."""
    from ceph_tpu.ops.profiler import DeviceProfiler
    from ceph_tpu.tools.vstart import Cluster
    STALL = 0.6
    # fresh host recorder: the bench phases above already compiled
    # their buckets, and the first OSD of this cluster must become
    # the host perf owner that ships compile reports monward
    DeviceProfiler.reset_host()
    try:
        with Cluster(n_osds=4, conf={
                "osd_ec_inject_compile_stall": STALL,
                "osd_ec_compile_stall_s": 0.3,
                "osd_ec_compile_storm_budget_s": 0.3,
                "osd_op_complaint_time": 0.2}) as c:
            client = c.client()
            client.set_ec_profile("cs21", {
                "plugin": "jax", "k": "2", "m": "1",
                "technique": "cauchy", "stripe_unit": "1024"})
            client.create_pool("cspool", "erasure",
                               erasure_code_profile="cs21", pg_num=2)
            io = client.open_ioctx("cspool")
            for i in range(3):
                io.write_full(f"cs{i}", bytes([i + 1]) * 4096)
            # COMPILE_STORM: reporter OSD ships the windowed compile
            # seconds on its next pgstats tick; poll mon health
            deadline = time.time() + 20.0
            storm = None
            while time.time() < deadline and storm is None:
                _rc, health = c.mon.handle_command({"prefix": "health"})
                storm = health.get("checks", {}).get("COMPILE_STORM")
                if storm is None:
                    time.sleep(0.25)
            out["compile_storm_raised"] = storm is not None
            # slow-op dump: the stalled write latched slow with the
            # first-compiled bucket and the launch id ON ITS TIMELINE
            # (the acceptance: the dump NAMES them).  blamed_stage
            # usually names the compile too, but on this loaded box a
            # first write's peering gap can legitimately out-gap the
            # injected stall — so blame naming it is reported, not
            # gated
            compiled_ev, lids, blamed = None, [], None
            for osd in c.osds:
                if osd is None:
                    continue
                for op in osd.op_tracker.dump_historic_slow_ops()["ops"]:
                    names = [e["event"] for e in op.get("events", [])]
                    ops_lids = [n for n in names
                                if n.startswith("launch(")]
                    comp = [n for n in names
                            if n.startswith("first_compile(")]
                    if comp and ops_lids:
                        compiled_ev = comp[0]
                        lids += ops_lids
                        if str(op.get("blamed_stage", "")
                               ).startswith("first_compile("):
                            blamed = op["blamed_stage"]
            out["compile_storm_slow_bucket"] = compiled_ev
            out["compile_storm_slow_blame"] = blamed
            out["compile_storm_launch_events"] = len(lids)
            if storm is None:
                return "injected compile stall raised no COMPILE_STORM"
            try:
                reported = float(storm["summary"].split("s of")[0])
            except (ValueError, IndexError):
                reported = 0.0
            if reported < STALL * 0.9:
                return (f"COMPILE_STORM under-reports the stall: "
                        f"{storm['summary']}")
            if compiled_ev is None:
                return ("no slow op carries a first_compile(bucket) "
                        "event")
            if not lids:
                return "no launch(<id>) events on any slow-op timeline"
            return None
    finally:
        # the injected singleton must not leak into later phases
        DeviceProfiler.reset_host()


def smoke_prewarm() -> dict:
    """Prewarm the smoke gates' jit buckets before any measurement
    (ISSUE 16: the 64pg-frac and profiler-overhead wander the PR-14/15
    bounded retries papered over was first-pass compile time landing
    inside the measured window).  Persistent compile cache on (the
    default dir, or CEPH_TPU_COMPILE_CACHE for hermetic CI), then the
    boot prewarm plan for the geometry the sweep gates use."""
    from ceph_tpu.ec.interface import Profile
    from ceph_tpu.ec.registry import ErasureCodePluginRegistry
    from ceph_tpu.ops import compile_cache, prewarm
    compile_cache.enable()
    status = {"enabled": compile_cache.enabled()}
    try:
        codec = ErasureCodePluginRegistry.instance().factory(
            "jax", Profile({"plugin": "jax", "k": "8", "m": "3"}))
        plan = prewarm.PrewarmPlan(codec, budget_s=float(
            os.environ.get("EC_SMOKE_PREWARM_BUDGET_S", "20")))
        st = plan.run()
        status.update({k: st[k] for k in
                       ("done", "compiles", "cache_hits", "truncated",
                        "total_s")})
        print(f"# smoke prewarm: {st['done']} buckets, "
              f"{st['compiles']} compiles, {st['cache_hits']} cache "
              f"hits, {st['total_s']}s", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — prewarm never fails smoke
        status["error"] = repr(e)
        print(f"# smoke prewarm failed (continuing cold): {e!r}",
              file=sys.stderr)
    return status


def run_smoke() -> int:
    """CPU-mode smoke for tier-1 (scripts/tier1.sh): tiny sizes, runs
    the full end-to-end benches, and asserts the published JSON keys
    exist with positive values — perf plumbing regressions fail here
    before a TPU round ever sees them."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ceph_tpu.utils.platform import ensure_usable_backend
    ensure_usable_backend(prefer_cpu=True)
    prewarm_status = smoke_prewarm()
    out = bench_end_to_end(on_tpu=False, passes=1, spacing=0.0)
    out["ec_smoke_prewarm"] = prewarm_status
    out["metric"] = "ec_write_pipeline_smoke"
    fused_why = check_fused_kernel_smoke(out)   # fills ec_fused_path
    clay_why = check_clay_repair_smoke(out)     # fills clay_* keys
    degraded_why = check_degraded_read_smoke(out)  # degraded_read_*
    storm_why = check_compile_storm_smoke(out)  # compile_storm_*
    print(json.dumps(out))
    missing = [k for k in SMOKE_KEYS
               if not isinstance(out.get(k), (int, float))
               or out[k] <= 0]
    if missing:
        print(f"# smoke FAILED: missing/invalid keys {missing}",
              file=sys.stderr)
        return 1
    # the CPU smoke must exercise the HOST hash fallback of deep scrub
    if out.get("ec_deep_scrub_host_bytes", 0) <= 0:
        print("# smoke FAILED: host crc fallback not exercised",
              file=sys.stderr)
        return 1
    # fused-kernel provenance guard (ISSUE 11): the headline fused
    # metric must come from the hier accumulator kernel, bit-exact —
    # a dispatch regression that silently falls back to XLA (or a
    # kernel change that breaks the L contract) fails here, not in a
    # TPU round
    if fused_why is not None:
        print(f"# smoke FAILED: {fused_why}", file=sys.stderr)
        return 1
    # repair-subsystem guards (docs/REPAIR.md): CLAY repair bit-parity
    # (device plan vs host plane-solver vs full decode, helper bytes
    # under the k-shard baseline) and the degraded-read SLO — client
    # reads during a shard-loss storm complete via reconstruct-on-read
    # through the batched decode path, zero loss, p99 published
    if clay_why is not None:
        print(f"# smoke FAILED: {clay_why}", file=sys.stderr)
        return 1
    if degraded_why is not None:
        print(f"# smoke FAILED: {degraded_why}", file=sys.stderr)
        return 1
    # flight-recorder guards (ISSUE 15, docs/TRACING.md "Device
    # plane"): the launch ledger must have recorded the run — at
    # least one launch, real runs/launch, queue-wait and device-time
    # percentiles, and at least one first-seen bucket in the compile
    # ledger — and the recorder itself must be ~free (profiler
    # on-vs-off ≤ PROF_OVERHEAD_MAX_PCT + measured noise, the PR 4
    # tracking-gate shape).  The injected compile-storm e2e
    # (COMPILE_STORM health + slow-op blame) rides storm_why.
    ledger = out.get("launch_ledger") or {}
    if not ledger.get("launches"):
        print(f"# smoke FAILED: launch_ledger empty ({ledger!r})",
              file=sys.stderr)
        return 1
    if not ledger.get("runs_per_launch"):
        print("# smoke FAILED: launch_ledger has no runs/launch",
              file=sys.stderr)
        return 1
    for pkey in ("device_ms_p50", "device_ms_p99",
                 "queue_wait_ms_p99"):
        if not isinstance(ledger.get(pkey), (int, float)):
            print(f"# smoke FAILED: launch_ledger missing {pkey} "
                  f"({ledger!r})", file=sys.stderr)
            return 1
    if not ledger.get("compile_buckets"):
        print("# smoke FAILED: compile ledger saw no first-seen "
              "bucket", file=sys.stderr)
        return 1
    pthresh = float(os.environ.get("PROF_OVERHEAD_MAX_PCT", "2.0"))
    pnoise = max(float(out.get("ec_write_profiler_noise_pct") or 0.0),
                 0.0)
    povh = out.get("ec_write_profiler_overhead_pct")
    # bounded retry (the 64pg box-wander rule): at smoke run lengths
    # this box's rate wanders far past any real per-launch cost, so a
    # failing single shot earns fresh interleaved A/Bs — a REAL
    # recorder regression (an alloc or lock per op, a sync) fails
    # every attempt
    # demoted workaround (ISSUE 16): with the gates prewarmed these
    # retries should never fire — each use is recorded in the row and
    # called out after the gates, so residual wander stays VISIBLE
    # instead of silently absorbed
    pretries_max = int(os.environ.get("PROF_OVERHEAD_RETRIES", "2"))
    pretries = pretries_max
    while (povh is None or povh > pthresh + pnoise) and pretries > 0:
        pretries -= 1
        print(f"# profiler overhead {povh}% > "
              f"{pthresh + pnoise:.2f}%: re-measuring "
              f"({pretries} retries left)", file=sys.stderr)
        povh, pnoise = measure_profiler_overhead()
        out["ec_write_profiler_overhead_pct"] = povh
        out["ec_write_profiler_noise_pct"] = pnoise
    out["ec_prof_overhead_retries_used"] = pretries_max - pretries
    if povh is None or povh > pthresh + pnoise:
        print(f"# smoke FAILED: profiler overhead {povh}% > "
              f"{pthresh + pnoise:.2f}% ({pthresh}% threshold + "
              f"{pnoise:.2f}% measured noise, best of retries)",
              file=sys.stderr)
        return 1
    # control-plane ledger overhead gate (ISSUE 19): same shape as
    # the profiler gate above — threshold + measured noise, bounded
    # re-measure on a failing single shot, retries-used published
    lthresh = float(os.environ.get("LEDGER_OVERHEAD_MAX_PCT", "2.0"))
    lnoise = max(float(out.get("ec_write_ledger_noise_pct") or 0.0),
                 0.0)
    lovh = out.get("ec_write_ledger_overhead_pct")
    lretries_max = int(os.environ.get("LEDGER_OVERHEAD_RETRIES", "2"))
    lretries = lretries_max
    while (lovh is None or lovh > lthresh + lnoise) and lretries > 0:
        lretries -= 1
        print(f"# ledger overhead {lovh}% > "
              f"{lthresh + lnoise:.2f}%: re-measuring "
              f"({lretries} retries left)", file=sys.stderr)
        lovh, lnoise = measure_ledger_overhead()
        out["ec_write_ledger_overhead_pct"] = lovh
        out["ec_write_ledger_noise_pct"] = lnoise
    out["ec_ledger_overhead_retries_used"] = lretries_max - lretries
    if lovh is None or lovh > lthresh + lnoise:
        print(f"# smoke FAILED: pg ledger overhead {lovh}% > "
              f"{lthresh + lnoise:.2f}% ({lthresh}% threshold + "
              f"{lnoise:.2f}% measured noise, best of retries)",
              file=sys.stderr)
        return 1
    # wire-plane ledger overhead gate (ISSUE 20): same shape as the
    # two gates above — threshold + measured noise, bounded re-measure
    # on a failing single shot, retries-used published
    mthresh = float(os.environ.get("MSGR_OVERHEAD_MAX_PCT", "2.0"))
    mnoise = max(float(out.get("ec_write_msgr_noise_pct") or 0.0),
                 0.0)
    movh = out.get("ec_write_msgr_overhead_pct")
    mretries_max = int(os.environ.get("MSGR_OVERHEAD_RETRIES", "2"))
    mretries = mretries_max
    while (movh is None or movh > mthresh + mnoise) and mretries > 0:
        mretries -= 1
        print(f"# msgr ledger overhead {movh}% > "
              f"{mthresh + mnoise:.2f}%: re-measuring "
              f"({mretries} retries left)", file=sys.stderr)
        movh, mnoise = measure_msgr_overhead()
        out["ec_write_msgr_overhead_pct"] = movh
        out["ec_write_msgr_noise_pct"] = mnoise
    out["ec_msgr_overhead_retries_used"] = mretries_max - mretries
    if movh is None or movh > mthresh + mnoise:
        print(f"# smoke FAILED: msgr ledger overhead {movh}% > "
              f"{mthresh + mnoise:.2f}% ({mthresh}% threshold + "
              f"{mnoise:.2f}% measured noise, best of retries)",
              file=sys.stderr)
        return 1
    if storm_why is not None:
        print(f"# smoke FAILED: {storm_why}", file=sys.stderr)
        return 1
    # many-PG continuous-batching guard (ISSUE 12): aggregate GB/s
    # through 64 PGs sharing the host launch queue must stay within
    # EC_64PG_MIN_FRAC (default 0.8 = the "within 20%" acceptance) of
    # the 1-PG pipelined point on the same harness, and the occupancy
    # counters must prove runs from different PGs actually coalesced
    # into shared launches — otherwise the queue is pass-through and
    # PG fan-out will shred TPU launch occupancy
    pg_min = float(os.environ.get("EC_64PG_MIN_FRAC", "0.8"))
    frac = out.get("ec_write_pipeline_64pg_frac")
    # best-of-N with bounded retry (PR 12/13 box-wander note): the
    # paired-ratio statistic still wanders when this smoke runs
    # back-to-back with other benches on a loaded 2-core box, so a
    # failing single-shot earns up to EC_64PG_RETRIES fresh sweeps —
    # the gate passes on the best showing, a REAL pass-through
    # regression fails every attempt
    retries_max = int(os.environ.get("EC_64PG_RETRIES", "2"))
    retries = retries_max
    while (not isinstance(frac, (int, float)) or frac < pg_min) \
            and retries > 0:
        retries -= 1
        print(f"# 64pg frac {frac!r} < {pg_min}: re-running the sweep "
              f"({retries} retries left)", file=sys.stderr)
        from ceph_tpu.tools.load_harness import run_ec_pg_sweep
        npg = out.get("ec_write_pipeline_64pg_n", 64)
        sweep = run_ec_pg_sweep(
            pg_counts=(1, npg), total_objs=2 * npg,
            objsize=1 << 16, chunk=1024, min_frac=0.0)
        if sweep["degradation_frac"] > (frac or 0.0):
            frac = sweep["degradation_frac"]
            out["ec_write_pipeline_64pg_frac"] = frac
            out["ec_write_pipeline_64pg_GBps"] = \
                sweep["agg_GBps"][str(npg)]
            out["ec_write_pipeline_64pg_base_GBps"] = \
                sweep["agg_GBps"]["1"]
            out["ec_host_queue_launches"] = sweep["launches"]
            out["ec_host_queue_runs_per_launch"] = \
                sweep["runs_per_launch"]
            out["ec_host_queue_cross_pg_launches"] = \
                sweep["cross_pg_launches"]
            out["ec_host_queue_occupancy_pct"] = \
                sweep["occupancy_pct"]
            out["ec_64pg_retried"] = True
    out["ec_64pg_retries_used"] = retries_max - retries
    retried = (out["ec_64pg_retries_used"]
               + out["ec_prof_overhead_retries_used"])
    if retried:
        # demoted workaround (ISSUE 16): the retry fired DESPITE the
        # prewarmed first pass — loud and machine-readable, because
        # with compiles out of the window a retry now means real
        # wander (box load, a recorder regression), not a cold jit
        # bucket
        print(f"# NOTE: smoke gates needed {retried} retr"
              f"{'y' if retried == 1 else 'ies'} with prewarmed "
              f"first pass (64pg={out['ec_64pg_retries_used']}, "
              f"prof_overhead={out['ec_prof_overhead_retries_used']})"
              f" — wander persisted past the compile fix",
              file=sys.stderr)
    if out.get("ec_64pg_retried"):
        # the row already printed before the gates: publish ONE
        # corrected row with the best retry's figures
        print(json.dumps(out))
    if not isinstance(frac, (int, float)) or frac < pg_min:
        print(f"# smoke FAILED: ec_write_pipeline_64pg_frac={frac!r} "
              f"< {pg_min} (aggregate GB/s degraded under PG fan-out, "
              f"best of retries)", file=sys.stderr)
        return 1
    if out.get("ec_host_queue_runs_per_launch", 0) <= 1.0:
        print(f"# smoke FAILED: launch queue did not coalesce "
              f"(runs/launch="
              f"{out.get('ec_host_queue_runs_per_launch')!r})",
              file=sys.stderr)
        return 1
    if out.get("ec_host_queue_cross_pg_launches", 0) < 1:
        print("# smoke FAILED: no launch coalesced runs from more "
              "than one PG", file=sys.stderr)
        return 1
    # tracking-overhead guard (docs/TRACING.md): always-on tracking
    # must cost < TRACK_OVERHEAD_MAX_PCT (default 2%) beyond the
    # run-to-run noise the untracked config itself shows at smoke
    # sizes — a real regression (per-event allocation, a sync, O(n)
    # dump work on the hot path) blows well past this; noise does not
    thresh = float(os.environ.get("TRACK_OVERHEAD_MAX_PCT", "2.0"))
    noise = max(float(out.get("ec_write_tracking_noise_pct") or 0.0),
                0.0)
    ovh = out.get("ec_write_tracking_overhead_pct")
    if ovh is None or ovh > thresh + noise:
        print(f"# smoke FAILED: tracking overhead {ovh}% > "
              f"{thresh + noise:.2f}% ({thresh}% threshold + "
              f"{noise:.2f}% measured noise)", file=sys.stderr)
        return 1
    # tail-latency guard (ISSUE 9): the per-stage percentile pipeline
    # must produce a positive end-to-end p99 AND per-stage p99s for
    # the stages the pipelined write path always crosses — a tracing
    # or percentile regression (events dropped, histograms empty,
    # quantile() broken) fails here, not in a TPU round
    stages = out.get("ec_write_stage_p99_ms") or {}
    p99 = out.get("ec_write_p99_ms")
    if not isinstance(p99, (int, float)) or p99 <= 0:
        print(f"# smoke FAILED: ec_write_p99_ms={p99!r}",
              file=sys.stderr)
        return 1
    # generous absolute ceiling (env-tunable): catches a pathological
    # tail regression (an accidental sync/sleep on the op path) while
    # absorbing slow-box noise at CPU smoke sizes
    p99_max = float(os.environ.get("TAIL_P99_MAX_MS", "500.0"))
    if p99 > p99_max:
        print(f"# smoke FAILED: ec_write_p99_ms={p99} > "
              f"TAIL_P99_MAX_MS={p99_max}", file=sys.stderr)
        return 1
    missing_stages = [s for s in ("ec_encode_launch", "commit")
                      if not stages.get(s, 0) or stages[s] <= 0]
    if missing_stages:
        print(f"# smoke FAILED: no per-stage p99 for {missing_stages} "
              f"(have {sorted(stages)})", file=sys.stderr)
        return 1
    # QoS isolation guard: a greedy tenant must not move the reserved
    # victim's p99 past QOS_ISOLATION_MAX (deterministic virtual-time
    # experiment — a scheduler regression, not load noise, fails it);
    # the FIFO contrast must stay ABOVE the bound or the experiment
    # itself lost its teeth
    from ceph_tpu.tools.load_harness import QOS_ISOLATION_MAX
    bound = float(os.environ.get("QOS_ISOLATION_MAX",
                                 str(QOS_ISOLATION_MAX)))
    ratio = out.get("qos_isolation_ratio")
    if not isinstance(ratio, (int, float)) or ratio > bound:
        print(f"# smoke FAILED: qos_isolation_ratio={ratio!r} > "
              f"{bound}", file=sys.stderr)
        return 1
    if out.get("qos_no_qos_ratio", 0) <= bound:
        print(f"# smoke FAILED: FIFO contrast ratio "
              f"{out.get('qos_no_qos_ratio')!r} <= {bound} — the "
              f"isolation experiment no longer stresses the victim",
              file=sys.stderr)
        return 1
    return 0


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.utils.platform import ensure_usable_backend

    backend = ensure_usable_backend()
    print(f"# backend: {backend}", file=sys.stderr)

    reg = ErasureCodePluginRegistry.instance()
    prof = {"k": str(K), "m": str(M), "technique": "cauchy"}
    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, SIZE, dtype=np.uint8).tobytes()

    jax_codec = reg.factory("jax", dict(prof))
    chunks = jax_codec.encode_prepare(payload)

    # CPU denominators: best available CPU plugin (native C if built)
    # for bare encode, and the SAME winning plugin + host crc pass for
    # the fused headline (the reference's two-pass configuration)
    cpu_best, cpu_codec = 0.0, None
    for plugin, p in (("isa", {"k": str(K), "m": str(M)}),
                      ("jerasure", {"k": str(K), "m": str(M),
                                    "technique": "cauchy_good"})):
        try:
            c = reg.factory(plugin, p)
            rate = time_encode_cpu(c, chunks)
            if rate > cpu_best:
                cpu_best, cpu_codec = rate, c
        except Exception as e:  # noqa: BLE001
            print(f"# cpu plugin {plugin} failed: {e}", file=sys.stderr)
    cpu_crc_best = 0.0
    if cpu_codec is not None:
        try:
            cpu_crc_best = time_encode_crc_cpu(cpu_codec, chunks)
        except Exception as e:  # noqa: BLE001
            print(f"# cpu fused denominator failed: {e}",
                  file=sys.stderr)

    import jax
    on_tpu = jax.default_backend() != "cpu"
    passes = int(os.environ.get("BENCH_PASSES", 5 if on_tpu else 1))
    spacing = float(os.environ.get("BENCH_SPACING_S",
                                   25.0 if on_tpu else 0.0))
    error = None
    samples = []
    for i in range(passes):
        if i and spacing:
            time.sleep(spacing)
        try:
            samples.append(time_encode_jax(jax_codec))
            print(f"# encode pass {i + 1}/{passes}: "
                  f"{samples[-1] / 1e9:.1f} GB/s", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"# jax encode pass {i + 1} failed: {e}",
                  file=sys.stderr)
            if error is None:
                error = f"encode: {e}"
    if samples:
        samples.sort()
        value = samples[len(samples) // 2]
        error = None            # any landed pass clears pass failures
    else:
        value = 0.0

    # fused parity+crc — the write path's real configuration (the OSD
    # always updates HashInfo; reference ECUtil.cc:172) and, since the
    # overlapped/accumulator kernel, THE HEADLINE: the same number of
    # spaced passes, its own published spread (min/max/n), the same
    # roofline elision gate (inside _slope_time).  TPU only (the
    # kernel is Mosaic-compiled) — CPU rows fall back to bare encode.
    extras = {}
    crc_samples = []
    if on_tpu:
        for i in range(passes):
            if i and spacing:
                time.sleep(spacing)
            try:
                crc_samples.append(time_encode_crc_jax(jax_codec))
                print(f"# encode+crc pass {i + 1}/{passes}: "
                      f"{crc_samples[-1] / 1e9:.1f} GB/s",
                      file=sys.stderr)
            except Exception as e:  # noqa: BLE001
                print(f"# encode+crc pass {i + 1} failed: {e}",
                      file=sys.stderr)
        crc_samples.sort()
        if not crc_samples and error is None:
            error = "encode+crc: all passes failed"
        if crc_samples:
            # only when fused passes actually landed: fused_path records
            # the kernel path the passes ran through, so a bare-encode
            # fallback row must not claim one
            try:
                # the autotuned cache entry the fused passes ran at
                # (tile, wb, extraction variant, combine depth) + the
                # kernel path it selects, so a perf move is
                # attributable to tuning vs kernel changes; the
                # headline must come from the hier kernel family,
                # never the XLA fallback
                point = jax_codec.fused_point()
                extras["fused_point"] = point
                extras["fused_path"] = "hier_acc" \
                    if point.get("combine") == "kernel" else "hier_lsub"
            except Exception:  # noqa: BLE001
                pass

    # decode-1/2/3 tracked alongside the headline (BASELINE.json
    # north_star; reference `-w decode -e 1/2/3`)
    for e_count in (1, 2, 3):
        try:
            extras[f"decode{e_count}_GBps"] = round(
                time_decode_jax(jax_codec, e_count) / 1e9, 3)
        except Exception as e:  # noqa: BLE001
            print(f"# jax decode-{e_count} failed: {e}", file=sys.stderr)
            extras[f"decode{e_count}_GBps"] = None
            if error is None:
                error = f"decode-{e_count}: {e}"

    # end-to-end: client->ECBackend->memstore write pipeline (dispatch-
    # ahead vs sync A/B) + deep scrub — the full path, not just the
    # kernel (ISSUE 3; BENCH_r06+ tracks these alongside the headline)
    try:
        extras.update(bench_end_to_end(on_tpu, passes, spacing))
    except Exception as e:  # noqa: BLE001
        print(f"# end-to-end bench failed: {e}", file=sys.stderr)
        for key in SMOKE_KEYS:
            extras.setdefault(key, None)
        if error is None:
            error = f"end_to_end: {e}"

    # headline selection: the fused point when it landed (TPU rounds —
    # ISSUE 11 promotes it: the gap between fused and bare IS the tax
    # production writes pay), bare encode otherwise (CPU fallback).
    # Both series always publish their full spread under stable keys.
    bare = {
        "ec_encode_k8_m3_1MiB_GBps":
            round(value / 1e9, 3) if samples else None,
        "ec_encode_min_GBps":
            round(samples[0] / 1e9, 3) if samples else None,
        "ec_encode_max_GBps":
            round(samples[-1] / 1e9, 3) if samples else None,
        "ec_encode_n_passes": len(samples),
    }
    fused_value = crc_samples[len(crc_samples) // 2] \
        if crc_samples else None
    fused = {
        "ec_encode_crc_k8_m3_1MiB_GBps":
            round(fused_value / 1e9, 3) if crc_samples else None,
        "ec_encode_crc_min_GBps":
            round(crc_samples[0] / 1e9, 3) if crc_samples else None,
        "ec_encode_crc_max_GBps":
            round(crc_samples[-1] / 1e9, 3) if crc_samples else None,
        "ec_encode_crc_n_passes": len(crc_samples),
    }
    if crc_samples:
        metric, headline = "ec_encode_crc_k8_m3_1MiB", "fused_encode_crc"
        head_value, head_samples = fused_value, crc_samples
        denom = cpu_crc_best
    else:
        metric, headline = "ec_encode_k8_m3_1MiB", "bare_encode"
        head_value, head_samples = value, samples
        denom = cpu_best
    out = {
        "metric": metric,
        "value": round(head_value / 1e9, 3) if head_samples else 0.0,
        "unit": "GB/s",
        "headline": headline,
        "vs_baseline": round(head_value / denom, 3)
        if denom and head_samples else None,
        # spread of the spaced passes: two driver runs whose medians
        # fall inside each other's [min, max] agree
        "value_min":
            round(head_samples[0] / 1e9, 3) if head_samples else None,
        "value_max":
            round(head_samples[-1] / 1e9, 3) if head_samples else None,
        "n_passes": len(head_samples),
        "pass_spacing_s": spacing,
        # PINNED absolute denominators (fixed iters, median of repeats):
        # bare CPU encode, and encode + host crc pass for the fused row
        "cpu_abs_GBps": round(cpu_best / 1e9, 3) if cpu_best else None,
        "cpu_crc_abs_GBps":
            round(cpu_crc_best / 1e9, 3) if cpu_crc_best else None,
        # numerator is device-resident batched slope timing; denominator
        # is per-call synchronous CPU encode (includes Python dispatch) —
        # see BASELINE.md for the methodology note
        "baseline_method": "cpu_per_call_sync_fixed_iters",
        **bare,
        **fused,
        **extras,
    }
    if error is not None:
        out["error"] = error
    print(json.dumps(out))
    if error is not None:
        sys.exit(1)


if __name__ == "__main__":
    if "--smoke" in sys.argv[1:]:
        sys.exit(run_smoke())
    if "--multichip" in sys.argv[1:]:
        sys.exit(run_multichip())
    main()
